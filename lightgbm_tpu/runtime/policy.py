"""Autoscale/shed policy: the queue-depth gauge turned into a control loop.

ISSUE 11: PR 9 made the serving runtime observable (the
``lgbm_serve_queue_depth`` gauge IS the backpressure signal), but nothing
acted on it — overload handling was a fixed-knob affair (bounded queue,
fixed gather window).  This module closes the loop with the same
measure-then-act shape production serving stacks use:

* **Widen under pressure** — sustained queue depth above the high
  watermark widens the micro-batch gather window (more coalescing per
  device dispatch buys throughput at the cost of p50), stepping by
  ``widen_factor`` up to ``max_window_s``.  This is the "autoscale" axis
  available to a single replica: it scales the *work per dispatch*, the
  way adding a replica scales dispatches.
* **Shed the lowest class** — entering overload also flips load-shed
  mode: the serving runtime rejects the LOWEST priority class at
  admission with the machine-readable, retryable reason ``load_shed``
  (runtime/serving.py), protecting the paid classes' latency.
* **Hysteresis, not flapping** — transitions need ``patience``
  consecutive observations past a watermark, and the band between the
  watermarks is a deadband that resets both counters: a depth signal
  oscillating around one threshold cannot toggle the mode (pinned in
  tests/test_policy.py).
* **Every decision is evidence** — each transition lands in the metrics
  registry (``lgbm_policy_decisions_total{action}``, the
  ``lgbm_policy_window_seconds`` / ``lgbm_policy_shed_active`` gauges)
  AND in the caller's stage trail via the returned decision records, so
  a sim artifact or a doctor bundle can reconstruct exactly when and why
  the controller acted.

The controller itself is a pure, clock-free state machine (`observe`
takes a depth fraction, returns decision records) so the hysteresis
semantics are unit-testable without a runtime; `ServingRuntime` drives
it from its policy thread.  No jax / numpy at module scope.
"""
from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

from . import telemetry
from .resilience import wallclock

__all__ = ["AutoscaleShedPolicy", "CanaryPolicy", "FleetScalePolicy"]


class AutoscaleShedPolicy:
    """Hysteresis controller over the admission-queue depth fraction.

    Parameters
    ----------
    high_watermark / low_watermark:
        Queue-depth fractions (of ``max_queue``) bounding the deadband.
        ``observe`` counts consecutive samples above high (pressure) or
        below low (slack); samples inside the band reset both counters.
    patience:
        Consecutive samples past a watermark required before acting.
    min_window_s / max_window_s / widen_factor:
        The gather-window range the controller walks: each widen
        multiplies by ``widen_factor`` (capped), each narrow divides
        (floored).  ``window_s`` starts at ``min_window_s``.
    interval_s:
        How often the serving runtime's policy thread samples the depth
        (the controller itself is clock-free).
    """

    def __init__(self,
                 high_watermark: float = 0.75,
                 low_watermark: float = 0.25,
                 patience: int = 3,
                 min_window_s: float = 0.002,
                 max_window_s: float = 0.064,
                 widen_factor: float = 2.0,
                 interval_s: float = 0.05):
        if not (0.0 <= low_watermark < high_watermark <= 1.0):
            raise ValueError("need 0 <= low_watermark < high_watermark <= 1,"
                             " got %r / %r" % (low_watermark, high_watermark))
        if widen_factor <= 1.0:
            raise ValueError("widen_factor must be > 1")
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.patience = max(int(patience), 1)
        self.min_window_s = float(min_window_s)
        self.max_window_s = float(max_window_s)
        self.widen_factor = float(widen_factor)
        self.interval_s = float(interval_s)

        self.window_s = self.min_window_s
        self.shed_active = False
        # ISSUE 17: a fleet controller that can still ADD REPLICAS revokes
        # this permission — shedding is the last resort, latched only once
        # the fleet is at max_replicas.  Single-replica deployments keep
        # the PR 11 behavior (always allowed).
        self.shed_allowed = True
        self._above = 0
        self._below = 0
        self.decisions: List[Dict[str, Any]] = []

    # -- the state machine ---------------------------------------------------
    def observe(self, depth_frac: float) -> List[Dict[str, Any]]:
        """Feed one queue-depth sample (fraction of max_queue); returns
        the decision records this sample triggered ([] for hold).  The
        deadband between the watermarks resets both streak counters —
        that reset IS the anti-flap guarantee."""
        out: List[Dict[str, Any]] = []
        if depth_frac > self.high_watermark:
            self._above += 1
            self._below = 0
        elif depth_frac < self.low_watermark:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
            return out
        if self._above >= self.patience:
            self._above = 0
            if self.window_s < self.max_window_s:
                self.window_s = min(self.window_s * self.widen_factor,
                                    self.max_window_s)
                out.append(self._decide("widen", depth_frac))
            if not self.shed_active and self.shed_allowed:
                self.shed_active = True
                out.append(self._decide("shed_on", depth_frac))
        elif self._below >= self.patience:
            self._below = 0
            if self.window_s > self.min_window_s:
                self.window_s = max(self.window_s / self.widen_factor,
                                    self.min_window_s)
                out.append(self._decide("narrow", depth_frac))
            # shed releases only once the window is fully narrowed: the
            # cheap lever (coalescing) is given back before admission is
            elif self.shed_active:
                self.shed_active = False
                out.append(self._decide("shed_off", depth_frac))
        return out

    def _decide(self, action: str, depth_frac: float) -> Dict[str, Any]:
        rec = {"event": "policy_decision", "action": action,
               "window_s": round(self.window_s, 6),
               "shed_active": self.shed_active,
               "depth_frac": round(float(depth_frac), 4),
               "wallclock": wallclock()}
        self.decisions.append(rec)
        telemetry.counter("lgbm_policy_decisions_total").inc(action=action)
        telemetry.gauge("lgbm_policy_window_seconds").set(self.window_s)
        telemetry.gauge("lgbm_policy_shed_active").set(
            1.0 if self.shed_active else 0.0)
        return rec

    def allow_shed(self, allowed: bool) -> List[Dict[str, Any]]:
        """Grant or revoke the shed permission (ISSUE 17: the fleet
        controller grants it only at max replicas).  Revoking while shed
        is latched releases it immediately — a replica must not keep
        dropping its lowest class when the fleet has capacity to add."""
        self.shed_allowed = bool(allowed)
        if not self.shed_allowed and self.shed_active:
            self.shed_active = False
            return [self._decide("shed_off", 0.0)]
        return []

    def state(self) -> Dict[str, Any]:
        return {"window_s": self.window_s, "shed_active": self.shed_active,
                "shed_allowed": self.shed_allowed,
                "decisions": len(self.decisions)}


class FleetScalePolicy:
    """Hysteresis state machine over FLEET load: queue-depth fraction and
    windowed p99 latency (scraped from every replica's metrics registry)
    in, replica-count targets out (ISSUE 17).

    Same contract as `AutoscaleShedPolicy` — pure, clock-free, pinnable:

    * **Pressure** is mean queue-depth fraction above ``high_watermark``
      OR windowed p99 above ``slo_p99_s``; **slack** is depth below
      ``low_watermark`` AND p99 back under the SLO.  Anything in between
      is the deadband and resets both streak counters (the no-flap
      guarantee, pinned in tests/test_prodsim.py).
    * ``patience`` consecutive pressure samples raise ``target`` by one
      replica (capped at ``max_replicas``); ``scale_down_patience``
      consecutive slack samples lower it (floored at ``min_replicas``).
      Scale-down defaults to 2x the scale-up patience: capacity is
      cheap to keep for a few seconds and expensive to miss.
    * **Shed is the last resort**: only when ``target`` is pinned at
      ``max_replicas`` and pressure persists does the controller latch
      ``shed_on`` — the `FleetController` then grants the per-replica
      `AutoscaleShedPolicy` its shed permission.  On slack the shed
      latch releases BEFORE any replica is retired.

    Decision records carry the acting sample's evidence (depth fraction,
    p99, target) and land in ``lgbm_policy_decisions_total{action}``
    like every other policy decision.
    """

    def __init__(self,
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 slo_p99_s: float = 0.5,
                 high_watermark: float = 0.5,
                 low_watermark: float = 0.15,
                 patience: int = 3,
                 scale_down_patience: Optional[int] = None,
                 interval_s: float = 0.5):
        if not (1 <= int(min_replicas) <= int(max_replicas)):
            raise ValueError("need 1 <= min_replicas <= max_replicas, got"
                             " %r / %r" % (min_replicas, max_replicas))
        if not (0.0 <= low_watermark < high_watermark <= 1.0):
            raise ValueError("need 0 <= low_watermark < high_watermark <= 1,"
                             " got %r / %r" % (low_watermark, high_watermark))
        if slo_p99_s <= 0.0:
            raise ValueError("slo_p99_s must be > 0, got %r" % (slo_p99_s,))
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_p99_s = float(slo_p99_s)
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.patience = max(int(patience), 1)
        self.scale_down_patience = (self.patience * 2
                                    if scale_down_patience is None
                                    else max(int(scale_down_patience), 1))
        self.interval_s = float(interval_s)

        self.target = self.min_replicas
        self.shed_latched = False
        self._above = 0
        self._below = 0
        self.decisions: List[Dict[str, Any]] = []

    # -- the state machine ---------------------------------------------------
    def observe(self, depth_frac: float,
                p99_s: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one fleet sample (mean replica queue-depth fraction and
        the windowed p99 across replicas; None p99 = no completions in
        the window, judged on depth alone).  Returns the decision
        records this sample triggered ([] for hold)."""
        depth_frac = float(depth_frac)
        slo_breach = p99_s is not None and float(p99_s) > self.slo_p99_s
        pressure = depth_frac > self.high_watermark or slo_breach
        slack = depth_frac < self.low_watermark and not slo_breach
        out: List[Dict[str, Any]] = []
        if pressure:
            self._above += 1
            self._below = 0
        elif slack:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
            return out
        if self._above >= self.patience:
            self._above = 0
            if self.target < self.max_replicas:
                self.target += 1
                out.append(self._decide("scale_up", depth_frac, p99_s))
            elif not self.shed_latched:
                # at max replicas with pressure still rising: the ONLY
                # remaining lever is admission — latch fleet-wide shed
                self.shed_latched = True
                out.append(self._decide("shed_on", depth_frac, p99_s))
        elif self._below >= self.scale_down_patience:
            self._below = 0
            if self.shed_latched:
                # give admission back before retiring any capacity
                self.shed_latched = False
                out.append(self._decide("shed_off", depth_frac, p99_s))
            elif self.target > self.min_replicas:
                self.target -= 1
                out.append(self._decide("scale_down", depth_frac, p99_s))
        return out

    def _decide(self, action: str, depth_frac: float,
                p99_s: Optional[float]) -> Dict[str, Any]:
        rec = {"event": "fleet_decision", "action": action,
               "target": self.target, "shed_latched": self.shed_latched,
               "depth_frac": round(float(depth_frac), 4),
               "p99_s": None if p99_s is None else round(float(p99_s), 6),
               "wallclock": wallclock()}
        self.decisions.append(rec)
        telemetry.counter("lgbm_policy_decisions_total").inc(action=action)
        return rec

    def state(self) -> Dict[str, Any]:
        return {"target": self.target, "shed_latched": self.shed_latched,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "slo_p99_s": self.slo_p99_s,
                "decisions": len(self.decisions)}


class CanaryPolicy:
    """Hysteresis state machine judging a canary generation against the
    incumbent (ISSUE 12 stage three — the `AutoscaleShedPolicy` pattern
    applied to model QUALITY instead of queue depth).

    The serving runtime routes a configurable fraction of batches to a
    freshly published generation and feeds every batch outcome here:
    ``observe(kind, error=, latency_s=)`` with ``kind`` canary or
    incumbent, ``error`` the batch's observed prediction error (clients
    that submitted labels; None when no label rode the batch) and the
    batch latency.  The controller is pure and clock-free — decisions
    depend only on the observation sequence, so the hysteresis semantics
    are unit-testable without a runtime.

    * **Warm-up** — no judgment before ``min_samples`` canary AND
      ``min_samples`` incumbent observations (of each signal kind): a
      single unlucky batch must not kill a good model.
    * **Degradation** — a canary comparison round is degraded when its
      windowed mean error exceeds ``incumbent_mean * error_ratio +
      error_margin`` or its windowed mean latency exceeds
      ``incumbent_mean * latency_ratio``.  Means are over the last
      ``window`` observations per side (a bounded sliding window, so a
      canary that RECOVERS pulls its mean back down instead of being
      condemned by history).  ``patience`` CONSECUTIVE degraded rounds
      latch the ``rollback`` decision; any healthy round in between
      resets the streak (the anti-flap deadband, same contract as the
      autoscale controller).
    * **Promotion** — ``promote_after`` canary observations with no
      active degradation streak latch ``promote``: the canary becomes
      the incumbent and full traffic cuts over.

    Every decision lands in ``lgbm_canary_events_total{event}`` and in
    the returned records (the serving runtime writes them to its stage
    trail and, on rollback, into the publish directory's durable
    ROLLBACK marker).
    """

    def __init__(self,
                 min_samples: int = 8,
                 patience: int = 3,
                 error_ratio: float = 1.5,
                 error_margin: float = 0.02,
                 latency_ratio: float = 5.0,
                 promote_after: int = 64,
                 window: int = 64):
        if error_ratio < 1.0 or latency_ratio < 1.0:
            raise ValueError("error_ratio/latency_ratio must be >= 1")
        self.min_samples = max(int(min_samples), 1)
        self.patience = max(int(patience), 1)
        self.error_ratio = float(error_ratio)
        self.error_margin = float(error_margin)
        self.latency_ratio = float(latency_ratio)
        self.promote_after = max(int(promote_after), self.min_samples)
        self.window = max(int(window), self.min_samples)
        self.decisions: List[Dict[str, Any]] = []
        self.reset(None)

    def reset(self, generation: Optional[int]) -> None:
        """Arm for a new canary generation (old streaks must not carry
        over to a different model)."""
        self.generation = generation
        self._err = {"canary": collections.deque(maxlen=self.window),
                     "incumbent": collections.deque(maxlen=self.window)}
        self._lat = {"canary": collections.deque(maxlen=self.window),
                     "incumbent": collections.deque(maxlen=self.window)}
        self._streak = 0
        self._decided: Optional[str] = None
        self._canary_batches = 0

    # -- the state machine ---------------------------------------------------
    def observe(self, kind: str, error: Optional[float] = None,
                latency_s: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one batch outcome; returns the decision records this
        observation triggered ([] for hold).  `kind` is "canary" or
        "incumbent"."""
        if kind not in self._err:
            raise ValueError("kind must be canary or incumbent, got %r"
                             % kind)
        if self._decided is not None:
            return []
        if error is not None:
            self._err[kind].append(float(error))
        if latency_s is not None:
            self._lat[kind].append(float(latency_s))
        if kind != "canary":
            return []
        self._canary_batches += 1
        degraded = None
        ce, ie = self._err["canary"], self._err["incumbent"]
        if len(ce) >= self.min_samples and len(ie) >= self.min_samples:
            can_err = sum(ce) / len(ce)
            inc_err = sum(ie) / len(ie)
            if can_err > inc_err * self.error_ratio + self.error_margin:
                degraded = {"signal": "error", "canary": round(can_err, 6),
                            "incumbent": round(inc_err, 6)}
        cl, il = self._lat["canary"], self._lat["incumbent"]
        if degraded is None and len(cl) >= self.min_samples \
                and len(il) >= self.min_samples:
            can_lat = sum(cl) / len(cl)
            inc_lat = sum(il) / len(il)
            if can_lat > inc_lat * self.latency_ratio:
                degraded = {"signal": "latency",
                            "canary": round(can_lat, 6),
                            "incumbent": round(inc_lat, 6)}
        out: List[Dict[str, Any]] = []
        if degraded is not None:
            self._streak += 1
            if self._streak >= self.patience:
                self._decided = "rollback"
                out.append(self._decide("rollback", degraded))
        else:
            self._streak = 0
            if self._canary_batches >= self.promote_after:
                self._decided = "promote"
                out.append(self._decide("promote", None))
        return out

    def _decide(self, event: str,
                evidence: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        rec = {"event": "canary_" + event, "generation": self.generation,
               "canary_batches": self._canary_batches,
               "evidence": evidence, "wallclock": wallclock()}
        self.decisions.append(rec)
        telemetry.counter("lgbm_canary_events_total").inc(event=event)
        return rec

    def note_start(self, generation: int) -> Dict[str, Any]:
        """Record (and count) the canary window opening for `generation`."""
        self.reset(generation)
        rec = {"event": "canary_start", "generation": generation,
               "wallclock": wallclock()}
        self.decisions.append(rec)
        telemetry.counter("lgbm_canary_events_total").inc(event="start")
        return rec

    @property
    def decided(self) -> Optional[str]:
        """"rollback"/"promote" once latched for this generation."""
        return self._decided

    def state(self) -> Dict[str, Any]:
        ce, ie = self._err["canary"], self._err["incumbent"]
        return {"generation": self.generation,
                "canary_batches": self._canary_batches,
                "streak": self._streak, "decided": self._decided,
                "canary_mean_error": sum(ce) / len(ce) if ce else None,
                "incumbent_mean_error": sum(ie) / len(ie) if ie else None}
