"""Autoscale/shed policy: the queue-depth gauge turned into a control loop.

ISSUE 11: PR 9 made the serving runtime observable (the
``lgbm_serve_queue_depth`` gauge IS the backpressure signal), but nothing
acted on it — overload handling was a fixed-knob affair (bounded queue,
fixed gather window).  This module closes the loop with the same
measure-then-act shape production serving stacks use:

* **Widen under pressure** — sustained queue depth above the high
  watermark widens the micro-batch gather window (more coalescing per
  device dispatch buys throughput at the cost of p50), stepping by
  ``widen_factor`` up to ``max_window_s``.  This is the "autoscale" axis
  available to a single replica: it scales the *work per dispatch*, the
  way adding a replica scales dispatches.
* **Shed the lowest class** — entering overload also flips load-shed
  mode: the serving runtime rejects the LOWEST priority class at
  admission with the machine-readable, retryable reason ``load_shed``
  (runtime/serving.py), protecting the paid classes' latency.
* **Hysteresis, not flapping** — transitions need ``patience``
  consecutive observations past a watermark, and the band between the
  watermarks is a deadband that resets both counters: a depth signal
  oscillating around one threshold cannot toggle the mode (pinned in
  tests/test_policy.py).
* **Every decision is evidence** — each transition lands in the metrics
  registry (``lgbm_policy_decisions_total{action}``, the
  ``lgbm_policy_window_seconds`` / ``lgbm_policy_shed_active`` gauges)
  AND in the caller's stage trail via the returned decision records, so
  a sim artifact or a doctor bundle can reconstruct exactly when and why
  the controller acted.

The controller itself is a pure, clock-free state machine (`observe`
takes a depth fraction, returns decision records) so the hysteresis
semantics are unit-testable without a runtime; `ServingRuntime` drives
it from its policy thread.  No jax / numpy at module scope.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from . import telemetry
from .resilience import wallclock

__all__ = ["AutoscaleShedPolicy"]


class AutoscaleShedPolicy:
    """Hysteresis controller over the admission-queue depth fraction.

    Parameters
    ----------
    high_watermark / low_watermark:
        Queue-depth fractions (of ``max_queue``) bounding the deadband.
        ``observe`` counts consecutive samples above high (pressure) or
        below low (slack); samples inside the band reset both counters.
    patience:
        Consecutive samples past a watermark required before acting.
    min_window_s / max_window_s / widen_factor:
        The gather-window range the controller walks: each widen
        multiplies by ``widen_factor`` (capped), each narrow divides
        (floored).  ``window_s`` starts at ``min_window_s``.
    interval_s:
        How often the serving runtime's policy thread samples the depth
        (the controller itself is clock-free).
    """

    def __init__(self,
                 high_watermark: float = 0.75,
                 low_watermark: float = 0.25,
                 patience: int = 3,
                 min_window_s: float = 0.002,
                 max_window_s: float = 0.064,
                 widen_factor: float = 2.0,
                 interval_s: float = 0.05):
        if not (0.0 <= low_watermark < high_watermark <= 1.0):
            raise ValueError("need 0 <= low_watermark < high_watermark <= 1,"
                             " got %r / %r" % (low_watermark, high_watermark))
        if widen_factor <= 1.0:
            raise ValueError("widen_factor must be > 1")
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.patience = max(int(patience), 1)
        self.min_window_s = float(min_window_s)
        self.max_window_s = float(max_window_s)
        self.widen_factor = float(widen_factor)
        self.interval_s = float(interval_s)

        self.window_s = self.min_window_s
        self.shed_active = False
        self._above = 0
        self._below = 0
        self.decisions: List[Dict[str, Any]] = []

    # -- the state machine ---------------------------------------------------
    def observe(self, depth_frac: float) -> List[Dict[str, Any]]:
        """Feed one queue-depth sample (fraction of max_queue); returns
        the decision records this sample triggered ([] for hold).  The
        deadband between the watermarks resets both streak counters —
        that reset IS the anti-flap guarantee."""
        out: List[Dict[str, Any]] = []
        if depth_frac > self.high_watermark:
            self._above += 1
            self._below = 0
        elif depth_frac < self.low_watermark:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
            return out
        if self._above >= self.patience:
            self._above = 0
            if self.window_s < self.max_window_s:
                self.window_s = min(self.window_s * self.widen_factor,
                                    self.max_window_s)
                out.append(self._decide("widen", depth_frac))
            if not self.shed_active:
                self.shed_active = True
                out.append(self._decide("shed_on", depth_frac))
        elif self._below >= self.patience:
            self._below = 0
            if self.window_s > self.min_window_s:
                self.window_s = max(self.window_s / self.widen_factor,
                                    self.min_window_s)
                out.append(self._decide("narrow", depth_frac))
            # shed releases only once the window is fully narrowed: the
            # cheap lever (coalescing) is given back before admission is
            elif self.shed_active:
                self.shed_active = False
                out.append(self._decide("shed_off", depth_frac))
        return out

    def _decide(self, action: str, depth_frac: float) -> Dict[str, Any]:
        rec = {"event": "policy_decision", "action": action,
               "window_s": round(self.window_s, 6),
               "shed_active": self.shed_active,
               "depth_frac": round(float(depth_frac), 4),
               "wallclock": wallclock()}
        self.decisions.append(rec)
        telemetry.counter("lgbm_policy_decisions_total").inc(action=action)
        telemetry.gauge("lgbm_policy_window_seconds").set(self.window_s)
        telemetry.gauge("lgbm_policy_shed_active").set(
            1.0 if self.shed_active else 0.0)
        return rec

    def state(self) -> Dict[str, Any]:
        return {"window_s": self.window_s, "shed_active": self.shed_active,
                "decisions": len(self.decisions)}
