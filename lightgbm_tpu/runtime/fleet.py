"""Elastic serving fleet: an SLO-driven autoscaling controller over
`ServingRuntime` replica subprocesses (ISSUE 17).

PR 16 made one replica wire-speed; this module makes *N of them* an
elastic unit.  Three pieces, one file, because they share the spawn
protocol:

* **`FleetController`** — spawns and retires replica subprocesses
  against an SLO.  Every ``interval_s`` it scrapes each replica's
  ``/metrics.json`` (the same prod-sim scrape path an operator's
  Prometheus would use — the controller has NO private channel into a
  replica), aggregates queue-depth fraction and a *windowed* p99 (the
  ``lgbm_serve_latency_seconds`` histogram delta between scrapes, so
  the signal tracks the last window instead of being drowned by the
  cumulative past), and feeds a `runtime.policy.FleetScalePolicy`
  hysteresis state machine.  ``scale_up`` spawns a replica; its
  ``LGBM_TPU_SPAWN_ORDINAL`` rides the environment so the
  ``die_at_spawn:K`` fault can target exactly the K-th fleet spawn.
  ``scale_down`` retires the newest ready replica (SIGTERM → graceful
  drain; its final metrics snapshot is kept so the fleet ledger never
  loses a dead replica's counters).  A replica that dies un-retired —
  including a ``die_at_spawn`` corpse that prewarmed but never reported
  ready — is detected by reaping and relaunched while the target
  demands it.  Shedding is LAST resort: ``shed_allowed`` reaches
  replicas through the shared ``fleet_state.json`` and is granted only
  when the policy latches ``shed_on`` at ``max_replicas`` — below max
  the correct response to pressure is another replica, not dropped
  requests (`AutoscaleShedPolicy.allow_shed`).
* **the `--replica` entrypoint** — one serving replica as a process:
  builds a `ServingRuntime` from a JSON spec (model zoo + quotas +
  bounded residency + shed policy), rides the PR 15 warm-start seam
  ($LGBM_TPU_COMPILE_CACHE + published shape manifests +
  prewarm-before-admit), fronts it with a binary `WireTCPServer`,
  publishes its ports atomically to an endpoint file, and polls
  ``fleet_state.json`` for the shed grant.  SIGTERM drains gracefully
  (wire front closed first, then the runtime, which exports its warm
  manifests for the next spawn).
* **`FleetClient`** — the LoadGenerator-compatible front door: the
  same ``submit(...).wait()`` future contract as `ServingRuntime`, but
  each request travels the PR 16 binary wire to a ready replica
  (round-robin), so one loadgen drives the whole fleet.  A replica
  dying mid-request is retried on a peer (bounded by the deadline
  budget); rejection frames are re-raised as `ServeRejected` with the
  request's priority class attached, preserving loadgen's
  machine-readability contract.

Reaction-time accounting: an *episode* opens at the first pressure
sample (depth above the high watermark or windowed p99 above the SLO)
and closes at the first scrape with neither — the span lands in
``lgbm_fleet_reaction_seconds`` and the controller's ledger, so
"scale-up reaction ≤ N s" is a measured, regression-trackable number
(helper/bench_history.py collates it across SIM_r*.json).

Everything here is stdlib + numpy; jax stays in the replica processes.
"""
from __future__ import annotations

import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry
from .policy import FleetScalePolicy
from .resilience import wallclock
from .serving import ServeRejected
from ..utils.log import Log

__all__ = ["FleetController", "FleetClient", "ReplicaHandle",
           "replica_main"]


def _atomic_write_json(path: str, obj: Dict[str, Any]) -> None:
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _http_get_json(port: int, path: str, timeout: float = 2.0
                   ) -> Optional[Dict[str, Any]]:
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d%s" % (port, path),
                timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:           # noqa: BLE001 — scrape loss is a signal gap
        return None


def _healthz_ok(port: int, timeout: float = 2.0) -> bool:
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % port,
                timeout=timeout) as resp:
            return resp.status == 200
    except Exception:           # noqa: BLE001 — warming answers 503
        return False


# ---------------------------------------------------------------------------
# replica handle
# ---------------------------------------------------------------------------

#: scheduler boost a WARMING replica runs at: on a contended box the
#: spawn-to-ready path (interpreter + model load + prewarm compiles) is
#: the thing a fleet-wide SLO breach is waiting on, so it briefly
#: outranks the serving plane — spawned through ``nice -n -2`` (needs
#: CAP_SYS_NICE; GNU nice degrades to 0 without it) and reniced back to
#: 0 by `replica_main` once ready
PREWARM_NICE_BOOST = 2


def _which(cmd: str) -> Optional[str]:
    for d in os.environ.get("PATH", "/usr/bin:/bin").split(os.pathsep):
        p = os.path.join(d, cmd)
        if os.access(p, os.X_OK):
            return p
    return None


class ReplicaHandle:
    """One replica subprocess as the controller sees it: the Popen, the
    spawn ordinal, readiness, and the LAST metrics snapshot (kept after
    death so the ledger never loses a dead replica's counters)."""

    def __init__(self, name: str, proc: subprocess.Popen, ordinal: int,
                 endpoint_path: str):
        self.name = name
        self.proc = proc
        self.ordinal = ordinal
        self.endpoint_path = endpoint_path
        self.spawned_mono = time.monotonic()
        self.ready = False
        self.ready_mono: Optional[float] = None
        self.retiring = False
        self.term_mono: Optional[float] = None
        self.dead = False
        self.stopped_mono: Optional[float] = None
        self.endpoint: Optional[Dict[str, Any]] = None
        self.last_snapshot: Optional[Dict[str, Any]] = None
        self.last_hist: Optional[Dict[str, Any]] = None

    @property
    def metrics_port(self) -> Optional[int]:
        return self.endpoint.get("metrics_port") if self.endpoint else None

    @property
    def wire_port(self) -> Optional[int]:
        return self.endpoint.get("wire_port") if self.endpoint else None

    @property
    def wire_uds(self) -> Optional[str]:
        """The replica's UDS wire path (the SHM handshake plane), when
        it published one — same-host clients prefer it."""
        return self.endpoint.get("wire_uds") if self.endpoint else None

    def replica_seconds(self, now_mono: float) -> float:
        end = self.stopped_mono if self.stopped_mono is not None \
            else now_mono
        return max(end - self.spawned_mono, 0.0)


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class FleetController:
    """Spawn/retire `--replica` subprocesses against an SLO.

    `spec` is the replica spec dict the entrypoint consumes (see
    `replica_main`); it is written once to ``<fleet_dir>/replica.json``
    and every spawn points at it.  `policy` supplies min/max replicas
    and the hysteresis; the controller is the *actuator* — the decision
    logic stays in the clock-free, unit-tested state machine."""

    def __init__(self, fleet_dir: str, spec: Dict[str, Any],
                 policy: Optional[FleetScalePolicy] = None,
                 interval_s: float = 0.5,
                 spawn_grace_s: float = 60.0,
                 drain_grace_s: float = 10.0,
                 env: Optional[Dict[str, str]] = None,
                 log=Log):
        self.fleet_dir = os.path.abspath(fleet_dir)
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.spec_path = os.path.join(self.fleet_dir, "replica.json")
        _atomic_write_json(self.spec_path, spec)
        self.spec = spec
        self.policy = policy or FleetScalePolicy()
        self.interval_s = float(interval_s)
        self.spawn_grace_s = float(spawn_grace_s)
        self.drain_grace_s = float(drain_grace_s)
        self.env = dict(env or {})
        self.log = log
        self.state_path = os.path.join(self.fleet_dir, "fleet_state.json")
        self._write_state(False)

        self.replicas: List[ReplicaHandle] = []       # live (incl. spawning)
        self.retired: List[ReplicaHandle] = []        # dead + retired
        self._ordinal = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._max_queue = int(spec.get("max_queue", 256))

        # ledger
        self.events: List[Dict[str, Any]] = []
        self.timeline: List[Dict[str, Any]] = []
        self.reactions_s: List[float] = []
        self._pressure_since: Optional[float] = None
        self._t0 = time.monotonic()
        self._replica_seconds_done = 0.0
        self.relaunches = 0
        self.scale_ups = 0
        self.scale_downs = 0
        # crash-loop guard: a replica dying before EVER reporting ready
        # backs the next spawn off (doubling, capped) so a broken spec
        # cannot fork-bomb the box; any replica reaching ready resets it
        self._spawn_backoff_s = 0.0
        self._spawn_backoff_until = 0.0
        # lock-free endpoint snapshot for the client hot path (list
        # replacement is atomic; a tick-stale entry just retries a peer)
        self._eps_cache: List[Tuple[str, int]] = []
        # endpoint -> UDS path for replicas that published one (the SHM
        # ring handshake plane; same replacement-is-atomic discipline)
        self._uds_cache: Dict[Tuple[str, int], str] = {}

    # -- state file the replicas poll ---------------------------------------
    def _write_state(self, shed_allowed: bool) -> None:
        _atomic_write_json(self.state_path,
                           {"shed_allowed": bool(shed_allowed),
                            "wallclock": wallclock()})

    # -- spawn / retire / reap ----------------------------------------------
    def _event(self, action: str, **extra: Any) -> None:
        rec = {"event": "fleet", "action": action,
               "t_s": round(time.monotonic() - self._t0, 3),
               "wallclock": wallclock()}
        rec.update(extra)
        self.events.append(rec)
        telemetry.counter("lgbm_fleet_scale_events_total").inc(action=action)

    def _spawn(self, reason: str = "scale_up") -> ReplicaHandle:
        self._ordinal += 1
        name = "replica-%03d" % self._ordinal
        ep_path = os.path.join(self.fleet_dir, name + ".endpoint.json")
        try:
            os.unlink(ep_path)
        except OSError:
            pass
        env = dict(os.environ)
        env.update(self.env)
        # the replica must resolve THIS package even when spawned with a
        # different cwd (the fleet dir)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH",
                                                            "")
        # the fault seam: die_at_spawn:K targets the K-th FLEET spawn —
        # a per-process counter could never see K>1, so the ordinal
        # rides the environment
        env["LGBM_TPU_SPAWN_ORDINAL"] = str(self._ordinal)
        log_path = os.path.join(self.fleet_dir, name + ".log")
        logf = open(log_path, "ab")
        argv = [sys.executable, "-m", "lightgbm_tpu.runtime.fleet",
                "--replica", self.spec_path,
                "--endpoint", ep_path,
                "--fleet-state", self.state_path]
        nice = _which("nice")
        if nice:
            # the prewarm sprint starts at exec so the boost covers the
            # interpreter + import phase too; GNU nice degrades to
            # niceness 0 with a warning when CAP_SYS_NICE is missing
            argv = [nice, "-n", str(-PREWARM_NICE_BOOST)] + argv
        proc = subprocess.Popen(
            argv, stdout=logf, stderr=subprocess.STDOUT, env=env,
            cwd=self.fleet_dir)
        logf.close()
        h = ReplicaHandle(name, proc, self._ordinal, ep_path)
        self.replicas.append(h)
        self._event(reason if reason == "relaunch" else "spawn",
                    replica=name, ordinal=self._ordinal, pid=proc.pid)
        return h

    def _refresh_eps(self) -> None:
        live = [h for h in self.replicas
                if h.ready and not h.retiring and h.wire_port is not None]
        self._eps_cache = [("127.0.0.1", h.wire_port) for h in live]
        self._uds_cache = {("127.0.0.1", h.wire_port): h.wire_uds
                           for h in live if h.wire_uds}

    def _retire(self, h: ReplicaHandle) -> None:
        h.retiring = True
        try:
            h.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
        self._refresh_eps()
        self._event("retire", replica=h.name, pid=h.proc.pid)

    def _finish(self, h: ReplicaHandle) -> None:
        """Move a dead handle to the retired list, closing its
        replica-seconds account."""
        h.dead = True
        h.stopped_mono = time.monotonic()
        self._replica_seconds_done += h.replica_seconds(h.stopped_mono)
        if h in self.replicas:
            self.replicas.remove(h)
        self.retired.append(h)
        self._refresh_eps()

    def _reap(self) -> None:
        now = time.monotonic()
        for h in list(self.replicas):
            rc = h.proc.poll()
            if rc is None:
                continue
            was_ready = h.ready
            self._finish(h)
            if h.retiring:
                self._event("retired", replica=h.name, returncode=rc)
                continue
            # un-asked-for death (fault churn, die_at_spawn corpse, OOM):
            # relaunch while the target demands it
            self.relaunches += 1
            self._event("death", replica=h.name, returncode=rc,
                        was_ready=was_ready)
            if not was_ready:
                self._spawn_backoff_s = min(
                    max(self._spawn_backoff_s * 2, 1.0), 10.0)
                self._spawn_backoff_until = now + self._spawn_backoff_s
            if len(self.replicas) < self.policy.target \
                    and now >= self._spawn_backoff_until:
                self._spawn(reason="relaunch")
        # a retiring replica that ignores SIGTERM past the drain grace
        # gets the axe — an elastic fleet cannot leak processes
        for h in list(self.replicas):
            if h.retiring and h.proc.poll() is None:
                if h.term_mono is None:
                    h.term_mono = now
                elif now - h.term_mono > self.drain_grace_s:
                    try:
                        h.proc.kill()
                    except OSError:
                        pass

    def _check_ready(self) -> None:
        now = time.monotonic()
        for h in self.replicas:
            if h.ready or h.retiring:
                continue
            if h.endpoint is None and os.path.exists(h.endpoint_path):
                try:
                    with open(h.endpoint_path) as fh:
                        h.endpoint = json.load(fh)
                except (OSError, ValueError):
                    h.endpoint = None
            if h.endpoint is not None and h.metrics_port \
                    and _healthz_ok(h.metrics_port):
                h.ready = True
                h.ready_mono = now
                self._spawn_backoff_s = 0.0
                self._spawn_backoff_until = 0.0
                self._event("ready", replica=h.name,
                            spawn_to_ready_s=round(now - h.spawned_mono, 3))
            elif now - h.spawned_mono > self.spawn_grace_s:
                # never-ready corpse with a live pid: kill and let the
                # reaper relaunch
                try:
                    h.proc.kill()
                except OSError:
                    pass

    # -- the scrape → aggregate → decide loop -------------------------------
    @staticmethod
    def _snapshot_hist(snap: Dict[str, Any], family: str
                       ) -> Dict[str, Any]:
        """Sum one histogram family across ALL label series of one
        replica's /metrics.json snapshot into a Histogram.state()-shaped
        dict (buckets come from the METRIC_TABLE declaration — the
        snapshot wire format carries counts only)."""
        edges = list(telemetry.LATENCY_BUCKETS_S)
        counts = [0] * len(edges)
        total = 0
        hsum = 0.0
        fam = (snap.get("metrics") or {}).get(family) or {}
        for entry in fam.get("series", []):
            cts = entry.get("counts") or []
            for i, c in enumerate(cts[:len(counts)]):
                counts[i] += int(c)
            total += int(entry.get("count", 0))
            hsum += float(entry.get("sum", 0.0))
        return {"buckets": edges, "counts": counts, "sum": hsum,
                "count": total}

    @staticmethod
    def _snapshot_gauge(snap: Dict[str, Any], family: str) -> float:
        fam = (snap.get("metrics") or {}).get(family) or {}
        return float(sum(float(e.get("value", 0.0))
                         for e in fam.get("series", [])))

    def _scrape(self) -> Tuple[float, Optional[float], int]:
        """One sweep: scrape every ready replica, return
        (fleet depth fraction, windowed p99 or None, replicas scraped)."""
        depth = 0.0
        scraped = 0
        window = {"buckets": list(telemetry.LATENCY_BUCKETS_S),
                  "counts": [0] * len(telemetry.LATENCY_BUCKETS_S),
                  "sum": 0.0, "count": 0}
        for h in self.replicas:
            if not h.ready or h.metrics_port is None:
                continue
            snap = _http_get_json(h.metrics_port, "/metrics.json")
            if snap is None:
                continue
            scraped += 1
            h.last_snapshot = snap
            depth += self._snapshot_gauge(snap, "lgbm_serve_queue_depth")
            hist = self._snapshot_hist(snap, "lgbm_serve_latency_seconds")
            if h.last_hist is not None:
                delta = telemetry.state_delta(hist, h.last_hist)
            else:
                delta = hist
            h.last_hist = hist
            for i, c in enumerate(delta["counts"]):
                window["counts"][i] += max(int(c), 0)
            window["count"] += max(int(delta["count"]), 0)
            window["sum"] += max(float(delta["sum"]), 0.0)
        if scraped == 0:
            return 0.0, None, 0
        depth_frac = depth / max(scraped * self._max_queue, 1)
        p99 = telemetry.quantile_from_state(window, 0.99) \
            if window["count"] > 0 else None
        return min(depth_frac, 1.0), p99, scraped

    def _apply(self, decisions: List[Dict[str, Any]]) -> None:
        for d in decisions:
            action = d["action"]
            if action == "scale_up":
                # count the decision; the paced top-up in _tick does the
                # actual spawn (one warming replica at a time — on a
                # contended box N concurrent prewarms each take N times
                # longer than one, so pacing lands capacity SOONER)
                self.scale_ups += 1
            elif action == "scale_down":
                self.scale_downs += 1
                # retire the NEWEST ready replica: the oldest carry the
                # warmest caches and the longest uptime
                ready = [h for h in self.replicas
                         if h.ready and not h.retiring]
                if ready:
                    self._retire(max(ready, key=lambda h: h.spawned_mono))
            elif action == "shed_on":
                self._write_state(True)
                self._event("shed_on")
            elif action == "shed_off":
                self._write_state(False)
                self._event("shed_off")

    def _tick(self) -> None:
        with self._lock:
            self._reap()
            self._check_ready()
            depth_frac, p99, scraped = self._scrape()
            decisions = []
            if scraped > 0:
                decisions = self.policy.observe(depth_frac, p99_s=p99)
                self._apply(decisions)
            # top the fleet up toward the target, PACED: at most one
            # warming replica at a time (covers scale_up decisions,
            # min_replicas at start, and deaths the reaper saw).  The
            # next spawn launches when the previous one reports ready —
            # serialized prewarms finish faster than contended ones
            alive = [h for h in self.replicas if not h.retiring]
            warming = sum(1 for h in alive if not h.ready)
            if len(alive) < self.policy.target and warming == 0 \
                    and time.monotonic() >= self._spawn_backoff_until:
                self._spawn()
            # reaction episodes: breach sample opens, all-clear closes
            now = time.monotonic()
            pressure = (depth_frac > self.policy.high_watermark
                        or (p99 is not None and p99 > self.policy.slo_p99_s))
            if pressure and self._pressure_since is None \
                    and scraped > 0:
                self._pressure_since = now
            elif not pressure and self._pressure_since is not None \
                    and scraped > 0:
                span = now - self._pressure_since
                self._pressure_since = None
                self.reactions_s.append(round(span, 3))
                telemetry.histogram(
                    "lgbm_fleet_reaction_seconds").observe(span)
            n_ready = sum(1 for h in self.replicas
                          if h.ready and not h.retiring)
            n_spawning = sum(1 for h in self.replicas
                             if not h.ready and not h.retiring)
            n_retiring = sum(1 for h in self.replicas if h.retiring)
            g = telemetry.gauge("lgbm_fleet_replicas")
            g.set(n_ready, state="ready")
            g.set(n_spawning, state="spawning")
            g.set(n_retiring, state="retiring")
            self._refresh_eps()
            self.timeline.append({
                "t_s": round(now - self._t0, 3),
                "ready": n_ready, "spawning": n_spawning,
                "retiring": n_retiring, "target": self.policy.target,
                "depth_frac": round(depth_frac, 4),
                "p99_s": None if p99 is None else round(p99, 6),
                "shed_latched": self.policy.shed_latched,
            })

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:   # noqa: BLE001 — the control loop
                # must survive a scrape/spawn hiccup; losing the loop
                # IS the outage
                self.log.warning("fleet: tick failed: %s: %s",
                                 type(e).__name__, e)
            self._stop.wait(self.interval_s)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetController":
        with self._lock:
            while len(self.replicas) < self.policy.min_replicas:
                self._spawn(reason="spawn")
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-controller",
                                        daemon=True)
        self._thread.start()
        return self

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 120.0) -> int:
        """Block until `n` (default min_replicas) replicas are ready."""
        want = int(n if n is not None else self.policy.min_replicas)
        deadline = time.monotonic() + timeout
        got = 0
        while time.monotonic() < deadline:
            with self._lock:
                got = sum(1 for h in self.replicas
                          if h.ready and not h.retiring)
            if got >= want:
                return got
            time.sleep(0.1)
        raise TimeoutError("fleet: %d/%d replicas ready after %.0fs"
                           % (got, want, timeout))

    def ready_endpoints(self) -> List[Tuple[str, int]]:
        """Lock-free: the client hot path reads the last tick's
        snapshot; a stale entry costs one retry, not a lock convoy."""
        return self._eps_cache

    def uds_path_for(self, addr: Tuple[str, int]) -> Optional[str]:
        """The replica's UDS wire path for a ready endpoint, if it
        published one — the door to the shared-memory ring transport
        for same-host clients (None → TCP only)."""
        return self._uds_cache.get(addr)

    def stop(self) -> Dict[str, Any]:
        self._eps_cache = []
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        with self._lock:
            if self._pressure_since is not None:
                # a pressure episode still open at teardown counts in
                # full — stopping mid-breach must not hide the breach
                span = time.monotonic() - self._pressure_since
                self._pressure_since = None
                self.reactions_s.append(round(span, 3))
                telemetry.histogram(
                    "lgbm_fleet_reaction_seconds").observe(span)
            for h in list(self.replicas):
                if h.proc.poll() is None:
                    try:
                        h.proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
            deadline = time.monotonic() + self.drain_grace_s
            while time.monotonic() < deadline and any(
                    h.proc.poll() is None for h in self.replicas):
                time.sleep(0.1)
            for h in list(self.replicas):
                if h.proc.poll() is None:
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
                    h.proc.wait(timeout=5)
                self._finish(h)
        return self.report()

    # -- ledger ---------------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            live = sum(h.replica_seconds(now) for h in self.replicas)
            total = self._replica_seconds_done + live
            return {
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "relaunches": self.relaunches,
                "replica_seconds": round(total, 3),
                "reactions_s": list(self.reactions_s),
                "scale_up_reaction_s_max": max(self.reactions_s)
                if self.reactions_s else None,
                "events": list(self.events),
                "timeline": list(self.timeline),
                "policy": self.policy.state(),
            }


# ---------------------------------------------------------------------------
# LoadGenerator-compatible fleet client
# ---------------------------------------------------------------------------

class _FleetResult:
    """The slice of `ServeResult` the loadgen waiter and verifier read,
    rebuilt from a decoded wire response."""

    __slots__ = ("values", "generation", "model_id", "served_by",
                 "latency_s", "stages", "model_trace")

    def __init__(self, rec: Dict[str, Any]):
        # the wire client's values view is only valid until its next
        # call — copy before the connection is reused
        v = np.array(rec["values"], copy=True)
        if v.ndim == 2 and v.shape[1] == 1:
            # the wire frame is always [rows, cols]; restore the
            # in-process ServeResult convention (1-D for single-output
            # objectives) so the byte-verifier's reference shape matches
            v = v[:, 0]
        self.values = v
        self.generation = int(rec["generation"])
        self.model_id = rec.get("model", "default")
        self.served_by = rec.get("served_by", "device")
        self.latency_s = float(rec.get("latency_s", 0.0))
        self.stages = dict(rec.get("stages") or {})
        self.model_trace = None


class _FleetFuture:
    """`submit()`'s return: the same wait-or-raise contract as the
    in-process request object."""

    __slots__ = ("enqueued", "priority", "_ev", "_rec", "_exc")

    def __init__(self, priority: int = 0) -> None:
        self.enqueued = time.monotonic()
        self.priority = int(priority)
        self._ev = threading.Event()
        self._rec: Optional[_FleetResult] = None
        self._exc: Optional[BaseException] = None

    def _resolve(self, rec: Optional[_FleetResult],
                 exc: Optional[BaseException]) -> None:
        self._rec = rec
        self._exc = exc
        self._ev.set()

    def wait(self, timeout: Optional[float] = None) -> _FleetResult:
        if not self._ev.wait(timeout):
            raise ServeRejected("client_timeout", retryable=True,
                                priority=self.priority,
                                detail="fleet client gave up waiting")
        if self._exc is not None:
            raise self._exc
        assert self._rec is not None
        return self._rec


class FleetClient:
    """Drive a whole fleet through one LoadGenerator: `submit` matches
    `ServingRuntime.submit`'s future contract, but each request rides
    the PR 16 binary wire to a ready replica, round-robin.  A replica
    dying mid-request retries on a peer inside the deadline budget;
    rejection frames re-raise as `ServeRejected` WITH the request's
    priority class (the wire rejection frame doesn't carry it — the
    client knows what it sent), preserving loadgen's machine-readability
    gate."""

    def __init__(self, controller: FleetController, workers: int = 16,
                 predict_deadline_s: float = 30.0,
                 request_timeout_s: float = 35.0,
                 prefer_shm: bool = True):
        from .wire import WireClient            # lazy: client-side only
        self._WireClient = WireClient
        self._ShmClient = None
        if prefer_shm:
            try:
                from .shm_ring import ShmClient
                self._ShmClient = ShmClient
            except ImportError:
                pass                  # non-Linux: sockets only
        self.controller = controller
        self.predict_deadline_s = float(predict_deadline_s)
        self.request_timeout_s = float(request_timeout_s)
        self._q: "queue.Queue" = queue.Queue()
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._stop = threading.Event()
        self._workers = [threading.Thread(target=self._worker,
                                          name="fleet-client-%d" % i,
                                          daemon=True)
                         for i in range(int(workers))]
        for t in self._workers:
            t.start()

    # -- the LoadGenerator seam ----------------------------------------------
    def submit(self, X: np.ndarray, deadline_s: Optional[float] = None,
               model_id: str = "default", priority: int = 0,
               traceparent: Optional[str] = None) -> _FleetFuture:
        fut = _FleetFuture(priority)
        self._q.put((fut, np.ascontiguousarray(X, dtype=np.float32),
                     model_id, int(priority)))
        return fut

    def _pick(self, skip: Optional[Tuple[str, int]] = None
              ) -> Optional[Tuple[str, int]]:
        eps = self.controller.ready_endpoints()
        if skip is not None and len(eps) > 1:
            eps = [e for e in eps if e != skip] or eps
        if not eps:
            return None
        with self._rr_lock:
            self._rr += 1
            return eps[self._rr % len(eps)]

    def _worker(self) -> None:
        conns: Dict[Tuple[str, int], Any] = {}
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            fut, X, model_id, priority = item
            self._serve_one(conns, fut, X, model_id, priority)

    def _serve_one(self, conns: Dict[Tuple[str, int], Any], fut, X,
                   model_id: str, priority: int) -> None:
        deadline = fut.enqueued + self.request_timeout_s
        last_err: Optional[BaseException] = None
        addr: Optional[Tuple[str, int]] = None
        while time.monotonic() < deadline:
            addr = self._pick(skip=addr)
            if addr is None:
                time.sleep(0.05)
                continue
            cli = conns.get(addr)
            try:
                if cli is None:
                    # same-host replicas that published a UDS path get
                    # the shared-memory ring; ANY setup failure falls
                    # back to the socket plane transparently (a fleet
                    # must serve, not insist on a transport)
                    uds = self.controller.uds_path_for(addr)
                    if uds is not None and self._ShmClient is not None:
                        try:
                            cli = self._ShmClient(
                                uds, timeout=self.request_timeout_s)
                        except Exception:    # noqa: BLE001 — fallback
                            cli = None
                    if cli is None:
                        cli = self._WireClient(addr, timeout=self.
                                               request_timeout_s)
                    conns[addr] = cli
                rec = cli.request_once(X, model_id=model_id,
                                       priority=priority)
            except Exception as e:   # noqa: BLE001 — dead replica,
                # torn connection, refused port: drop the conn, try a
                # peer inside the budget
                last_err = e
                dead = conns.pop(addr, None)
                if dead is not None:
                    try:
                        dead.close()
                    except Exception:        # noqa: BLE001
                        pass
                continue
            if rec.get("error") == "rejected":
                # the wire rejection frame carries no priority class —
                # the client attaches the one it sent, preserving
                # loadgen's machine-readability gate
                fut._resolve(None, ServeRejected(
                    rec.get("reason", "rejected"),
                    retryable=bool(rec.get("retryable", True)),
                    priority=priority,
                    retry_after_s=rec.get("retry_after_s")))
                return
            fut._resolve(_FleetResult(rec), None)
            return
        fut._resolve(None, ServeRejected(
            "fleet_unavailable", retryable=True, priority=priority,
            detail=str(last_err) if last_err else "no ready replica"))

    def close(self) -> None:
        self._stop.set()
        for _ in self._workers:
            self._q.put(None)
        for t in self._workers:
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# the --replica subprocess entrypoint
# ---------------------------------------------------------------------------

def replica_main(spec_path: str, endpoint_path: str,
                 fleet_state_path: Optional[str] = None) -> int:
    """One serving replica as a process: ServingRuntime (model zoo +
    bounded residency + shed policy) fronted by a binary wire server,
    ports published atomically to `endpoint_path`, `fleet_state.json`
    polled for the shed grant, SIGTERM drains gracefully."""
    from .policy import AutoscaleShedPolicy
    from .serving import ServingRuntime
    from .wire import WireTCPServer, WireUnixServer

    with open(spec_path) as fh:
        spec = json.load(fh)

    pol = None
    if spec.get("shed_policy", True):
        pol = AutoscaleShedPolicy(
            high_watermark=float(spec.get("shed_high", 0.85)),
            low_watermark=float(spec.get("shed_low", 0.5)),
            patience=int(spec.get("shed_patience", 3)))
        # the fleet grants shedding only at max replicas; until the
        # grant arrives, pressure must surface as queue depth the
        # controller can see, not silently dropped requests
        pol.allow_shed(bool(spec.get("shed_allowed", False)))
    rt = ServingRuntime(
        models=spec.get("models"),
        model_file=spec.get("model_file"),
        params=spec.get("params"),
        raw_score=bool(spec.get("raw_score", False)),
        response_dtype=spec.get("response_dtype", "float32"),
        max_queue=int(spec.get("max_queue", 256)),
        max_batch_rows=int(spec.get("max_batch_rows", 4096)),
        batch_window_s=float(spec.get("batch_window_s", 0.002)),
        default_deadline_s=float(spec.get("default_deadline_s", 10.0)),
        predict_deadline_s=float(spec.get("predict_deadline_s", 30.0)),
        poll_interval_s=float(spec.get("poll_interval_s", 0.2)),
        priority_levels=int(spec.get("priority_levels", 3)),
        quotas=spec.get("quotas"),
        max_resident=int(spec.get("max_resident", 0)),
        policy=pol,
        metrics_port=0)
    rt.start()                       # die_at_spawn fires in here
    srv = WireTCPServer(rt, port=0)
    srv_thread = threading.Thread(target=srv.serve_forever,
                                  kwargs={"poll_interval": 0.2},
                                  name="replica-wire", daemon=True)
    srv_thread.start()
    # the UDS/SHM plane beside TCP: same runtime, same frames, but
    # same-host clients can upgrade any connection to a shared-memory
    # ring.  AF_UNIX paths cap near 108 bytes, and a bind failure must
    # never take the replica down — fall back to TCP-only.
    usrv = None
    uds_path = (endpoint_path[:-len(".endpoint.json")]
                if endpoint_path.endswith(".endpoint.json")
                else os.path.splitext(endpoint_path)[0]) + ".sock"
    if bool(spec.get("wire_uds", True)) and len(uds_path) < 100:
        try:
            usrv = WireUnixServer(rt, uds_path)
            threading.Thread(target=usrv.serve_forever,
                             kwargs={"poll_interval": 0.2},
                             name="replica-wire-uds", daemon=True).start()
        except OSError:
            usrv = None
    ep = {
        "pid": os.getpid(),
        "metrics_port": rt.metrics_port,
        "wire_port": srv.port,
        "wallclock": wallclock()}
    if usrv is not None:
        ep["wire_uds"] = uds_path
    _atomic_write_json(endpoint_path, ep)
    try:
        # end of the prewarm sprint: rejoin the serving plane at normal
        # priority (raising nice needs no privilege; no-op when the
        # spawn-side boost was unavailable)
        boost = -os.nice(0)
        if boost > 0:
            os.nice(boost)
    except OSError:
        pass

    stop = threading.Event()

    def _term(_sig, _frm) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    last_shed: Optional[bool] = None
    while not stop.is_set():
        if fleet_state_path:
            try:
                with open(fleet_state_path) as fh:
                    allowed = bool(json.load(fh).get("shed_allowed",
                                                     False))
            except (OSError, ValueError):
                allowed = None       # torn read: keep the last grant
            if allowed is not None and allowed != last_shed:
                rt.set_shed_allowed(allowed)
                last_shed = allowed
        stop.wait(0.25)

    # drain: close the front door first, then the runtime (rejects the
    # queue explicitly and exports warm manifests for the next spawn)
    srv.shutdown()
    srv.server_close()
    if usrv is not None:
        usrv.shutdown()
        usrv.server_close()
    rt.stop()
    return 0


def _main(argv: List[str]) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="python -m lightgbm_tpu.runtime."
                                      "fleet")
    ap.add_argument("--replica", metavar="SPEC_JSON",
                    help="run one replica from this spec file")
    ap.add_argument("--endpoint", metavar="PATH",
                    help="where the replica publishes its ports")
    ap.add_argument("--fleet-state", metavar="PATH", default=None,
                    help="fleet_state.json to poll for the shed grant")
    args = ap.parse_args(argv)
    if not args.replica or not args.endpoint:
        ap.error("--replica SPEC_JSON and --endpoint PATH are required")
    return replica_main(args.replica, args.endpoint, args.fleet_state)


if __name__ == "__main__":          # pragma: no cover — subprocess entry
    sys.exit(_main(sys.argv[1:]))
