"""Shared-memory ring transport: syscall-free serving (ISSUE 20).

PR 16's binary wire plane made the per-request cost a 40-byte header, a
CRC and two socket syscalls.  On a same-host deployment — the fleet's
replicas, any sidecar — those syscalls and the kernel socket-buffer
copy ARE the remaining cost.  This module removes them: a per-client
shared-memory segment holding a pair of single-producer/single-consumer
byte rings, requests written by the client directly into the mapped
region in the PR 16 frame format and admitted as a numpy VIEW of the
segment (`ServingRuntime.submit_view` — no recv, no copy, no
allocation), responses packed by the connection's `_ResponseScratch`
straight into the response ring.

**Handshake rides the PR 16 socket.**  The client connects to the
ordinary `WireUnixServer` and sends one `MSG_SHM_SETUP` frame whose
payload is the packed segment header (`RING_HEADER_FIELDS` — pinned
field-for-field against the `WIRE_RING_FIELDS` token line +
`LGBMWireRingHeader` struct in ``cpp/lightgbm_tpu_c_api.h`` by
``helper/check_wire_abi.py``).  The server acks, the client passes the
segment fd plus two eventfd doorbells over the socket with
``SCM_RIGHTS``, the server maps and validates, acks again, and the
socket stays open as the session's CONTROL channel: connection setup,
auth and teardown reuse the socket handshake, and peer death is an
EOF/HUP the server's doorbell poll sees immediately.

**Segment layout** (all little-endian; offsets carried in the header
so both sides agree by construction)::

    [0,  40)  segment header  (RING_HEADER_FIELDS, 40 bytes)
    [64, 256) request-ring control   -- 3 cache lines:
              tail u64 @ +0 | head u64 @ +64 | waiter u32 @ +128
    [256,448) response-ring control  -- same 3-line shape
    [448, 448+req_capacity)      request ring data   (client -> server)
    [.., .. + resp_capacity)     response ring data  (server -> client)

Head/tail are free-running u64 sequence counters on their own cache
lines (no false sharing); position = counter & (capacity-1).  Frames
are always CONTIGUOUS: a producer that cannot fit a frame before the
segment boundary writes a 4-byte wrap marker (0xFFFFFFFF — never a
valid frame magic) and skips to the ring start, so the consumer can
hand the runtime a contiguous zero-copy view.  Capacities are powers
of two, at least twice the largest frame.

**Doorbell protocol** (adaptive spin-then-eventfd): a consumer spins a
bounded wall-clock budget on the tail counter, then publishes a
``waiter`` flag, re-checks the ring (the lost-wakeup guard), and blocks
in ``poll([eventfd, control_socket])``.  A producer that observes
``waiter`` set clears it and writes the eventfd — exactly one syscall
per sleep/wake episode, ZERO when both sides stay hot.  The spin is
bounded, so an idle client costs nothing.  Every syscall the ring path
can make is counted (``lgbm_shm_doorbell_syscalls_total``); the bench
proves the steady-state count is zero per request.

**Contract edges** (all test-pinned in tests/test_shm_ring.py):
wraparound across the segment boundary; full-ring backpressure as a
typed RETRYABLE reject (``ring_full`` client-side before any byte
moves, ``resp_ring_full`` server-side — never a blocked server
thread); a CRC-corrupted in-ring frame rejected WITHOUT desyncing the
sequence counters (the frame boundary is still trustworthy, exactly
the socket plane's bad_crc semantics); and crashed-client reclamation:
peer death on the control socket drains the in-flight admissions,
unmaps the segment, closes every fd and counts the event
(``lgbm_shm_sessions_total{event="reclaimed"}``) — the `die_at_ring:K`
fault arms the soak.
"""
from __future__ import annotations

import gc
import mmap
import os
import select
import socket
import struct
import time
import zlib
from collections import deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from . import telemetry
from .wire import (HEADER_FMT, HEADER_SIZE, MAGIC, VERSION, MSG_REQUEST,
                   MSG_SHM_OK, MSG_SHM_SETUP, DTYPE_F32, RESP_META_SIZE,
                   MAX_PAYLOAD, MAX_COLS, WireFrameError, pack_header,
                   pack_reject, read_frame, unpack_response,
                   _ResponseScratch, _unpad_model_id, _pad_model_id)

__all__ = ["RING_HEADER_FIELDS", "RING_HEADER_FMT", "RING_HEADER_SIZE",
           "RING_MAGIC", "RING_VERSION", "ShmClient", "ShmError",
           "serve_handler", "stats_snapshot"]

#: the canonical segment-header layout — ``helper/check_wire_abi.py``
#: pins this tuple token-for-token against the ``WIRE_RING_FIELDS``
#: comment + ``LGBMWireRingHeader`` struct in cpp/lightgbm_tpu_c_api.h;
#: edit both together or the lint fails
RING_HEADER_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("magic", "4s"),
    ("version", "B"),
    ("flags", "B"),
    ("reserved", "H"),
    ("seg_size", "Q"),
    ("req_ctrl", "I"),
    ("req_offset", "I"),
    ("req_capacity", "I"),
    ("resp_ctrl", "I"),
    ("resp_offset", "I"),
    ("resp_capacity", "I"),
)
RING_HEADER_FMT = "<" + "".join(f for _n, f in RING_HEADER_FIELDS)
RING_HEADER_SIZE = struct.calcsize(RING_HEADER_FMT)     # 40 bytes
_RING_HEADER = struct.Struct(RING_HEADER_FMT)

RING_MAGIC = b"LGBR"
RING_VERSION = 1

CACHE_LINE = 64
CTRL_SIZE = 3 * CACHE_LINE       # tail line + head line + waiter line
CTRL_TAIL, CTRL_HEAD, CTRL_WAITER = 0, CACHE_LINE, 2 * CACHE_LINE
REQ_CTRL_OFF = CACHE_LINE        # header is padded out to one line
RESP_CTRL_OFF = REQ_CTRL_OFF + CTRL_SIZE
DATA_OFF = RESP_CTRL_OFF + CTRL_SIZE

WRAP_MARK = 0xFFFFFFFF           # never a valid frame magic ("LGBW")
MIN_CAPACITY = 1 << 12
MAX_CAPACITY = 1 << 28
DEFAULT_REQ_CAPACITY = 1 << 20
DEFAULT_RESP_CAPACITY = 1 << 20

_HEADER = struct.Struct(HEADER_FMT)
_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_ONE = (1).to_bytes(8, "little")

#: spin budget (seconds of wall clock) a consumer burns on the counters
#: before arming the doorbell and sleeping.  Small by default — an idle
#: session must cost nothing — and raised by the bench to measure the
#: syscall-free steady state.
SPIN_S_DEFAULT = 0.002


def _spin_budget_s() -> float:
    try:
        return float(os.environ.get("LGBM_TPU_SHM_SPIN_S",
                                    SPIN_S_DEFAULT))
    except ValueError:
        return SPIN_S_DEFAULT


class ShmError(RuntimeError):
    """A ring-protocol violation (torn setup, impossible offsets, a
    frame header that lies).  Fatal to the SESSION, never the server."""


def pack_ring_config(req_capacity: int = DEFAULT_REQ_CAPACITY,
                     resp_capacity: int = DEFAULT_RESP_CAPACITY) -> bytes:
    """The 40-byte segment header both sides agree on."""
    req_capacity, resp_capacity = int(req_capacity), int(resp_capacity)
    seg_size = DATA_OFF + req_capacity + resp_capacity
    return _RING_HEADER.pack(
        RING_MAGIC, RING_VERSION, 0, 0, seg_size,
        REQ_CTRL_OFF, DATA_OFF, req_capacity,
        RESP_CTRL_OFF, DATA_OFF + req_capacity, resp_capacity)


def unpack_ring_config(raw: bytes) -> Dict[str, int]:
    if len(raw) < RING_HEADER_SIZE:
        raise ShmError("short ring config: %d bytes" % len(raw))
    (magic, version, _flags, _resv, seg_size, req_ctrl, req_off, req_cap,
     resp_ctrl, resp_off, resp_cap) = _RING_HEADER.unpack_from(raw)
    if magic != RING_MAGIC:
        raise ShmError("bad ring magic %r" % magic)
    if version != RING_VERSION:
        raise ShmError("bad ring version %d" % version)
    for cap in (req_cap, resp_cap):
        if cap < MIN_CAPACITY or cap > MAX_CAPACITY or cap & (cap - 1):
            raise ShmError("ring capacity %d not a power of two in "
                           "[%d, %d]" % (cap, MIN_CAPACITY, MAX_CAPACITY))
    if (req_ctrl != REQ_CTRL_OFF or resp_ctrl != RESP_CTRL_OFF
            or req_off != DATA_OFF or resp_off != DATA_OFF + req_cap
            or seg_size != DATA_OFF + req_cap + resp_cap):
        raise ShmError("ring offsets disagree with the v%d layout"
                       % RING_VERSION)
    return {"seg_size": seg_size, "req_ctrl": req_ctrl,
            "req_offset": req_off, "req_capacity": req_cap,
            "resp_ctrl": resp_ctrl, "resp_offset": resp_off,
            "resp_capacity": resp_cap}


# ---------------------------------------------------------------------------
# the SPSC byte ring (one side of it)
# ---------------------------------------------------------------------------

class _Ring:
    """One direction's view of an SPSC byte ring in the mapped segment.
    The same object serves as producer (reserve/publish) on one side of
    the session and consumer (try_pop/advance) on the other — each
    process only ever uses one role per ring.

    Counter stores are single aligned 8-byte writes through the mmap;
    under CPython the interpreter serializes them and x86-TSO keeps the
    data-then-counter publish order — the compiled client uses explicit
    ``__atomic`` builtins for the same contract."""

    __slots__ = ("mm", "ctrl", "data", "cap", "mask", "wraps", "pending")

    def __init__(self, mm: mmap.mmap, ctrl_off: int, data_off: int,
                 cap: int):
        self.mm = mm
        self.ctrl = ctrl_off
        self.data = data_off
        self.cap = cap
        self.mask = cap - 1
        self.wraps = 0
        #: consumer-local peek cursor: bytes POPPED but not yet
        #: `advance`d (the shared head only moves when the frame's bytes
        #: are truly dead, so the producer can't reuse them while a
        #: zero-copy view is still in flight)
        self.pending = 0

    # counter plumbing ------------------------------------------------------
    def load_tail(self) -> int:
        return _U64.unpack_from(self.mm, self.ctrl + CTRL_TAIL)[0]

    def store_tail(self, v: int) -> None:
        _U64.pack_into(self.mm, self.ctrl + CTRL_TAIL, v)

    def load_head(self) -> int:
        return _U64.unpack_from(self.mm, self.ctrl + CTRL_HEAD)[0]

    def store_head(self, v: int) -> None:
        _U64.pack_into(self.mm, self.ctrl + CTRL_HEAD, v)

    def load_waiter(self) -> int:
        return _U32.unpack_from(self.mm, self.ctrl + CTRL_WAITER)[0]

    def store_waiter(self, v: int) -> None:
        _U32.pack_into(self.mm, self.ctrl + CTRL_WAITER, v)

    # producer --------------------------------------------------------------
    def reserve(self, need: int) -> Optional[Tuple[int, int, int]]:
        """Contiguous space for `need` bytes: (byte offset into the
        segment, pad consumed by the wrap, tail) — or None when the
        ring is full (the typed-backpressure seam)."""
        tail = self.load_tail()
        head = self.load_head()
        pos = tail & self.mask
        room = self.cap - pos
        pad = 0
        if need > room:
            pad = room
            pos = 0
        if need + pad > self.cap - (tail - head):
            return None
        return self.data + pos, pad, tail

    def publish(self, tail: int, pad: int, need: int) -> None:
        """Make the frame visible: write the wrap marker (if any), then
        ONE tail store covering pad+frame."""
        if pad >= 4:
            _U32.pack_into(self.mm, self.data + (tail & self.mask),
                           WRAP_MARK)
        if pad:
            self.wraps += 1
        self.store_tail(tail + pad + need)

    # consumer --------------------------------------------------------------
    def has_data(self) -> bool:
        return self.load_tail() != self.load_head() + self.pending

    def try_pop(self) -> Optional[Tuple[Tuple, int, int]]:
        """One frame if available: (header tuple, payload byte offset
        into the segment, span to advance by).  Validates only the
        FRAMING here (wrap marker, header bounds); the caller owns the
        protocol checks and the CRC."""
        head = self.load_head() + self.pending
        avail = self.load_tail() - head
        if avail == 0:
            return None
        pos = head & self.mask
        room = self.cap - pos
        skip = 0
        if room < HEADER_SIZE or (
                _U32.unpack_from(self.mm, self.data + pos)[0] == WRAP_MARK):
            skip = room
            pos = 0
            avail -= skip
            if avail <= 0:
                raise ShmError("wrap marker with no frame behind it")
            self.wraps += 1
        if avail < HEADER_SIZE:
            raise ShmError("torn frame header: %d of %d bytes published"
                           % (avail, HEADER_SIZE))
        hdr = _HEADER.unpack_from(self.mm, self.data + pos)
        payload_len = hdr[8]
        if payload_len > self.cap - HEADER_SIZE - skip \
                or payload_len > MAX_PAYLOAD:
            raise ShmError("frame payload_len %d cannot fit the ring"
                           % payload_len)
        total = HEADER_SIZE + payload_len
        if avail < total:
            raise ShmError("torn frame: %d of %d bytes published"
                           % (avail, total))
        self.pending += skip + total
        return hdr, self.data + pos + HEADER_SIZE, skip + total

    def advance(self, span: int) -> None:
        self.pending -= span
        self.store_head(self.load_head() + span)


# ---------------------------------------------------------------------------
# doorbell (adaptive spin -> eventfd)
# ---------------------------------------------------------------------------

class _Doorbell:
    """Consumer-side sleep/wake for one ring + the session's control
    socket.  Counts every syscall it makes — the 'syscall-free steady
    state' claim is measured, not asserted."""

    __slots__ = ("ring", "efd", "sock", "poller", "spin_s", "syscalls",
                 "label")

    def __init__(self, ring: _Ring, efd: int, sock: socket.socket,
                 label: str):
        self.ring = ring
        self.efd = efd
        self.sock = sock
        self.label = label
        self.poller = select.poll()
        self.poller.register(efd, select.POLLIN)
        if sock is not None:
            self.poller.register(sock.fileno(),
                                 select.POLLIN | select.POLLHUP)
        self.spin_s = _spin_budget_s()
        self.syscalls = 0

    def ring_peer(self, producer_ring: _Ring, peer_efd: int,
                  counter) -> None:
        """Producer side: wake the peer iff it published a waiter flag
        (cleared here so a burst costs ONE wakeup syscall)."""
        if producer_ring.load_waiter():
            producer_ring.store_waiter(0)
            try:
                os.write(peer_efd, _ONE)
            except (BlockingIOError, OSError):
                pass
            self.syscalls += 1
            counter.inc(op="ring")

    def wait(self, counter, timeout_s: Optional[float] = None) -> bool:
        """Block until the ring has data or the control socket trips.
        Returns True when data arrived, False on timeout; raises
        ShmError("peer_closed") when the peer hung up."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        spin_until = time.monotonic() + self.spin_s
        n = 0
        while True:
            if self.ring.has_data():
                return True
            n += 1
            if n & 0xFF == 0 and time.monotonic() > spin_until:
                break
        while True:
            self.ring.store_waiter(1)
            if self.ring.has_data():
                self.ring.store_waiter(0)
                return True
            self.syscalls += 1
            counter.inc(op="wait")
            events = self.poller.poll(250)
            self.ring.store_waiter(0)
            for fd, ev in events:
                if self.sock is not None and fd == self.sock.fileno():
                    raise ShmError("peer_closed")
                if fd == self.efd:
                    try:
                        os.read(self.efd, 8)
                        self.syscalls += 1
                        counter.inc(op="drain")
                    except (BlockingIOError, OSError):
                        pass
            if self.ring.has_data():
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False


# ---------------------------------------------------------------------------
# frame helpers shared by both sides
# ---------------------------------------------------------------------------

def _write_request(ring: _Ring, X: np.ndarray, model_id: str,
                   priority: int) -> Optional[int]:
    """Pack one request frame straight into the ring.  Returns the
    frame's total bytes, or None when the ring is full."""
    X = np.ascontiguousarray(np.atleast_2d(X), np.float32)
    n_rows, n_cols = X.shape
    payload_len = n_rows * n_cols * 4
    need = HEADER_SIZE + payload_len
    r = ring.reserve(need)
    if r is None:
        return None
    off, pad, tail = r
    mv = memoryview(ring.mm)
    try:
        mv[off + HEADER_SIZE:off + need] = memoryview(X).cast("B")
        crc = zlib.crc32(mv[off + HEADER_SIZE:off + need]) & 0xFFFFFFFF
    finally:
        mv.release()
    _HEADER.pack_into(ring.mm, off, MAGIC, VERSION, MSG_REQUEST,
                      DTYPE_F32, int(priority) & 0x0F,
                      _pad_model_id(model_id), n_rows, n_cols,
                      payload_len, crc)
    ring.publish(tail, pad, need)
    return need


def _write_reject(ring: _Ring, reason: str, retryable: bool,
                  retry_after_s: float, model_id: str,
                  wait_space_s: float = 5.0) -> bool:
    """Copy a (small) rejection frame into the ring, waiting briefly
    for space — rejects are the backpressure signal itself, so they get
    a bounded grace the data frames never do."""
    frame = pack_reject(reason, retryable=retryable,
                        retry_after_s=retry_after_s, model_id=model_id)
    deadline = time.monotonic() + wait_space_s
    while True:
        r = ring.reserve(len(frame))
        if r is not None:
            off, pad, tail = r
            ring.mm[off:off + len(frame)] = frame
            ring.publish(tail, pad, len(frame))
            return True
        if time.monotonic() > deadline:
            return False
        time.sleep(0.0005)


# ---------------------------------------------------------------------------
# server side: one session per MSG_SHM_SETUP frame on the UDS plane
# ---------------------------------------------------------------------------

#: process-wide session ledger the bench and tests read directly
#: (telemetry counters carry the same events for scrapes)
_STATS = {"sessions": 0, "reclaimed": 0, "closed": 0, "torn": 0,
          "rx_buffer_allocs": 0, "tx_buffer_allocs": 0}


def stats_snapshot() -> Dict[str, int]:
    return dict(_STATS)


class _Teardown(Exception):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _Session:
    """The server half of one client's ring pair: pop requests from the
    request ring, admit them as zero-copy views, pack responses (in
    request order — the rings are FIFO) straight into the response
    ring.  Runs on the connection handler's thread; one session per
    client, so a stalled client only ever blocks itself."""

    MAX_INFLIGHT = 64

    def __init__(self, sock: socket.socket, runtime, mm: mmap.mmap,
                 cfg: Dict[str, int], efd_req: int, efd_resp: int,
                 max_rows: int):
        self.sock = sock
        self.rt = runtime
        self.mm = mm
        self.cfg = cfg
        self.efd_req = efd_req
        self.efd_resp = efd_resp
        self.max_rows = max_rows
        self.req = _Ring(mm, cfg["req_ctrl"], cfg["req_offset"],
                         cfg["req_capacity"])
        self.resp = _Ring(mm, cfg["resp_ctrl"], cfg["resp_offset"],
                          cfg["resp_capacity"])
        self.bell = _Doorbell(self.req, efd_req, sock, "server")
        self.scratch = _ResponseScratch()
        self.inflight: deque = deque()
        self.frames = telemetry.counter("lgbm_shm_frames_total")
        self.bytes_total = telemetry.counter("lgbm_serve_bytes_total")
        self.doorbells = telemetry.counter(
            "lgbm_shm_doorbell_syscalls_total")
        self._scratch_allocs = 0

    # -- admission ----------------------------------------------------------
    def _admit_available(self) -> None:
        while len(self.inflight) < self.MAX_INFLIGHT:
            item = self.req.try_pop()          # raises ShmError on torn
            if item is None:
                return
            hdr, payload_off, span = item
            (magic, version, msg_type, dtype, flags, model_raw, n_rows,
             n_cols, payload_len, crc) = hdr
            model_id = _unpad_model_id(model_raw)
            if magic != MAGIC or version != VERSION:
                raise _Teardown("bad_frame")
            if msg_type != MSG_REQUEST or dtype != DTYPE_F32:
                raise _Teardown("bad_frame")
            if n_cols > MAX_COLS or n_rows > self.max_rows \
                    or n_rows < 1 or n_cols < 1 \
                    or payload_len != n_rows * n_cols * 4:
                raise _Teardown("bad_frame")
            self.bytes_total.inc(HEADER_SIZE + payload_len, path="shm",
                                 dir="rx")
            mv = memoryview(self.mm)
            crc_ok = zlib.crc32(
                mv[payload_off:payload_off + payload_len]) \
                & 0xFFFFFFFF == crc
            mv.release()
            if not crc_ok:
                # intact boundary, corrupt bytes: reject THIS frame,
                # keep the counters in sync (the socket plane's
                # non-fatal bad_crc class)
                self.frames.inc(outcome="bad_crc")
                self.inflight.append((None, span, "bad_crc", True, 0.0,
                                      model_id, n_rows))
                continue
            from .serving import ServeRejected
            try:
                # the zero-copy hand-off: a float32 view of the MAPPED
                # SEGMENT rides the admission queue; nothing was read,
                # copied or allocated on the way in
                X = np.frombuffer(self.mm, np.float32,
                                  count=n_rows * n_cols,
                                  offset=payload_off).reshape(n_rows,
                                                              n_cols)
                fut = self.rt.submit_view(X, model_id=model_id,
                                          priority=flags & 0x0F)
                self.inflight.append((fut, span, "", False, 0.0,
                                      model_id, n_rows))
            except ServeRejected as e:
                self.frames.inc(outcome="rejected")
                self.inflight.append((None, span, e.reason, e.retryable,
                                      e.retry_after_s or 0.0, model_id,
                                      n_rows))

    # -- completion ---------------------------------------------------------
    def _reserve_resp(self, need: int) -> Tuple[int, int, int]:
        r = self.resp.reserve(need)
        if r is not None:
            return r
        # response ring full: the client owns the drain.  Bounded
        # grace, watching the control socket — never an unbounded
        # block, never a server thread parked on a dead peer.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self.sock is not None:
                ev = self.bell.poller.poll(0)
                for fd, _e in ev:
                    if fd == self.sock.fileno():
                        raise _Teardown("peer_closed")
            time.sleep(0.0005)
            r = self.resp.reserve(need)
            if r is not None:
                return r
        raise _Teardown("resp_ring_stalled")

    def _respond_reject(self, reason: str, retryable: bool,
                        retry_after_s: float, model_id: str) -> None:
        if not _write_reject(self.resp, reason, retryable, retry_after_s,
                             model_id):
            raise _Teardown("resp_ring_stalled")
        self.frames.inc(outcome="rejected")

    def _complete_oldest(self) -> None:
        from .serving import ServeRejected
        fut, span, reason, retryable, retry_after, model_id, n_rows = \
            self.inflight[0]
        if fut is None:
            self._respond_reject(reason, retryable, retry_after, model_id)
        else:
            try:
                rec = fut.wait(timeout=self.rt.wire_wait_timeout_s)
                vals = np.asarray(rec.values).reshape(n_rows, -1)
                need = HEADER_SIZE + RESP_META_SIZE + vals.size * 4
                if need > self.resp.cap - CACHE_LINE:
                    self._respond_reject("resp_too_large", False, 0.0,
                                         model_id)
                else:
                    before = len(self.scratch._f32)
                    off, pad, tail = self._reserve_resp(need)
                    total = self.scratch.pack_response_into(
                        self.mm, off, vals, rec.generation, model_id,
                        rec.served_by, rec.latency_s, rec.stages,
                        rec.compiled)
                    self.resp.publish(tail, pad, total)
                    if len(self.scratch._f32) > before:
                        self._scratch_allocs += 1
                        _STATS["tx_buffer_allocs"] += 1
                    self.frames.inc(outcome="completed")
                    self.bytes_total.inc(total, path="shm", dir="tx")
            except ServeRejected as e:
                self._respond_reject(e.reason, e.retryable,
                                     e.retry_after_s or 0.0, model_id)
            except _Teardown:
                raise
            except Exception as e:      # noqa: BLE001 — wire error class
                self.rt.log.warning("shm: request failed: %s: %s",
                                    type(e).__name__, e)
                self._respond_reject("bad_request", False, 0.0, model_id)
        self.bell.ring_peer(self.resp, self.efd_resp, self.doorbells)
        self.inflight.popleft()
        # the request frame's bytes are dead only now that its response
        # is in the ring — free them in completion order
        self.req.advance(span)

    # -- the loop -----------------------------------------------------------
    def run(self) -> str:
        try:
            while True:
                self._admit_available()
                if self.inflight:
                    self._complete_oldest()
                    continue
                self.bell.wait(self.doorbells)   # raises on peer death
        except _Teardown as e:
            return e.reason
        except ShmError as e:
            return "peer_closed" if str(e) == "peer_closed" else "torn"
        except (OSError, ValueError):
            return "torn"

    def drain_inflight(self) -> int:
        """Resolve every admitted future before the segment goes away —
        the runtime may still be gathering views of the mapped bytes."""
        pending = 0
        while self.inflight:
            fut = self.inflight.popleft()[0]
            pending += 1
            if fut is None:
                continue
            try:
                fut.wait(timeout=self.rt.wire_wait_timeout_s)
            except Exception:           # noqa: BLE001 — result discarded
                pass
        return pending


def _recv_fds(sock: socket.socket, n: int,
              timeout_s: float = 15.0) -> Tuple[bytes, list]:
    old = sock.gettimeout()
    sock.settimeout(timeout_s)
    try:
        msg, fds, _flags, _addr = socket.recv_fds(sock, 16, n)
        return msg, list(fds)
    finally:
        sock.settimeout(old)


def serve_handler(handler, setup_payload: bytes) -> None:
    """Run one SHM session on a `_WireHandler`'s thread.  Called by the
    UDS wire server when a MSG_SHM_SETUP frame arrives; the handler's
    socket becomes the session's control channel and the function only
    returns when the session is over (the socket then closes)."""
    sessions = telemetry.counter("lgbm_shm_sessions_total")
    rt = handler.server.runtime
    sock = handler.connection
    mm = None
    fds: list = []
    try:
        cfg = unpack_ring_config(setup_payload)
    except ShmError as e:
        sessions.inc(event="rejected_setup")
        handler._send(pack_reject("shm_bad_setup: %s" % e,
                                  retryable=False),
                      telemetry.counter("lgbm_serve_bytes_total"), "shm")
        return
    reason = "torn"
    try:
        # ack #1: config accepted, send the fds now
        ack = pack_header(MSG_SHM_OK, "shm", 0, 0, setup_payload) \
            + setup_payload
        handler.wfile.write(ack)
        handler.wfile.flush()
        _msg, fds = _recv_fds(sock, 3)
        if len(fds) != 3:
            raise ShmError("expected 3 fds (segment, doorbell x2), "
                           "got %d" % len(fds))
        seg_fd, efd_req, efd_resp = fds
        if os.fstat(seg_fd).st_size != cfg["seg_size"]:
            raise ShmError("segment size disagrees with the config")
        mm = mmap.mmap(seg_fd, cfg["seg_size"])
        os.close(seg_fd)
        fds = [efd_req, efd_resp]
        if bytes(mm[:RING_HEADER_SIZE]) != setup_payload[
                :RING_HEADER_SIZE]:
            raise ShmError("segment header disagrees with the setup "
                           "frame")
        # ack #2: mapped and validated — the rings are live
        handler.wfile.write(ack)
        handler.wfile.flush()
        _STATS["sessions"] += 1
        sessions.inc(event="ready")
        sess = _Session(sock, rt, mm, cfg, efd_req, efd_resp,
                        handler.server.max_rows_per_frame)
        reason = sess.run()
        pending = sess.drain_inflight()
        if reason == "peer_closed":
            reason = "reclaimed" if pending else "closed"
        del sess
    except (ShmError, OSError, ValueError) as e:
        rt.log.warning("shm: session setup failed: %s", e)
        reason = "torn"
    finally:
        for fd in fds:
            try:
                os.close(fd)
            except OSError:
                pass
        leaked = False
        if mm is not None:
            # the runtime must hold no view of the segment when it is
            # unmapped; admissions were drained above, stragglers are
            # swept by the collector
            for _ in range(100):
                try:
                    mm.close()
                    break
                except BufferError:
                    gc.collect()
                    time.sleep(0.05)
            else:
                leaked = True
                rt.log.warning("shm: segment still referenced at "
                               "teardown — mapping leaked")
        _STATS[reason if reason in _STATS else "torn"] = \
            _STATS.get(reason if reason in _STATS else "torn", 0) + 1
        sessions.inc(event="leaked" if leaked else reason)


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

class ShmClient:
    """Ring-transport client with the `WireClient` surface: connect to
    a UDS wire server, negotiate a segment, then `request_once` /
    pipelined `submit_nowait`+`read_response` without a single data
    syscall.  A full request ring surfaces as the machine-readable
    retryable reject ``{"error": "rejected", "reason": "ring_full"}``
    before any byte moves — backpressure is the caller's signal, not a
    blocked thread."""

    def __init__(self, uds_path: str,
                 req_capacity: int = DEFAULT_REQ_CAPACITY,
                 resp_capacity: int = DEFAULT_RESP_CAPACITY,
                 timeout: float = 30.0):
        if not hasattr(os, "memfd_create") or not hasattr(os, "eventfd"):
            raise ShmError("shm transport needs Linux + Python >= 3.10")
        self.timeout = float(timeout)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(uds_path)
        self._rfile = self._sock.makefile("rb")
        self.inflight = 0
        self._mm = None
        self._fds = []
        cfg_bytes = pack_ring_config(req_capacity, resp_capacity)
        cfg = unpack_ring_config(cfg_bytes)
        try:
            self._sock.sendall(
                pack_header(MSG_SHM_SETUP, "shm", 0, 0, cfg_bytes)
                + cfg_bytes)
            self._expect_ok()
            seg_fd = os.memfd_create("lgbm-shm-ring")
            self._fds = [seg_fd]
            os.ftruncate(seg_fd, cfg["seg_size"])
            self._mm = mmap.mmap(seg_fd, cfg["seg_size"])
            self._mm[:RING_HEADER_SIZE] = cfg_bytes
            efd_req = os.eventfd(0, os.EFD_NONBLOCK)
            efd_resp = os.eventfd(0, os.EFD_NONBLOCK)
            self._fds += [efd_req, efd_resp]
            socket.send_fds(self._sock, [b"F"],
                            [seg_fd, efd_req, efd_resp])
            self._expect_ok()
            os.close(seg_fd)
            self._fds = [efd_req, efd_resp]
            self.efd_req, self.efd_resp = efd_req, efd_resp
            self.req = _Ring(self._mm, cfg["req_ctrl"],
                             cfg["req_offset"], cfg["req_capacity"])
            self.resp = _Ring(self._mm, cfg["resp_ctrl"],
                              cfg["resp_offset"], cfg["resp_capacity"])
            self.bell = _Doorbell(self.resp, efd_resp, self._sock,
                                  "client")
            self.doorbells = telemetry.counter(
                "lgbm_shm_doorbell_syscalls_total")
        except Exception:
            self.close()
            raise

    def _expect_ok(self) -> None:
        frame = read_frame(self._rfile)
        if frame is None:
            raise ShmError("server closed during shm handshake")
        hdr, payload = frame
        if hdr[2] != MSG_SHM_OK:
            out = unpack_response(hdr, bytes(payload))
            raise ShmError("shm setup rejected: %s"
                           % out.get("reason", hdr[2]))

    # -- producing ----------------------------------------------------------
    def submit_nowait(self, X: np.ndarray, model_id: str = "default",
                      priority: int = 0) -> Optional[Dict[str, Any]]:
        """Write one request frame; returns None on success or the
        typed retryable reject dict when the ring is full."""
        need = _write_request(self.req, X, model_id, priority)
        if need is None:
            return {"error": "rejected", "reason": "ring_full",
                    "retryable": True, "retry_after_s": 0.002}
        self.inflight += 1
        self.bell.ring_peer(self.req, self.efd_req, self.doorbells)
        from . import resilience
        resilience.maybe_die_at_ring(self.inflight)
        return None

    # -- consuming ----------------------------------------------------------
    def read_response(self,
                      timeout: Optional[float] = None) -> Dict[str, Any]:
        """Pop the next response frame (FIFO with the requests).  The
        returned values are copied out of the ring, so the frame's
        bytes are freed before this returns."""
        if not self.bell.wait(self.doorbells,
                              timeout if timeout is not None
                              else self.timeout):
            raise WireFrameError("shm_timeout", "no response in ring")
        item = self.resp.try_pop()
        if item is None:                # spurious wake
            return self.read_response(timeout)
        hdr, payload_off, span = item
        payload_len = hdr[8]
        payload = bytes(self._mm[payload_off:payload_off + payload_len])
        if zlib.crc32(payload) & 0xFFFFFFFF != hdr[9]:
            self.resp.advance(span)
            self.inflight -= 1
            raise WireFrameError("bad_crc", fatal=False)
        out = unpack_response(hdr, payload)
        self.resp.advance(span)
        self.inflight -= 1
        return out

    def request_once(self, X: np.ndarray, model_id: str = "default",
                     priority: int = 0) -> Dict[str, Any]:
        rej = self.submit_nowait(X, model_id, priority)
        if rej is not None:
            return rej
        return self.read_response()

    def predict(self, X: np.ndarray, model_id: str = "default",
                attempts: int = 3, priority: int = 0) -> Dict[str, Any]:
        """Retryable-reject backoff loop — `WireClient.predict` parity."""
        last: Optional[Dict[str, Any]] = None
        for a in range(max(attempts, 1)):
            out = self.request_once(X, model_id, priority=priority)
            if "error" not in out:
                return out
            last = out
            if not out.get("retryable"):
                break
            if a + 1 < max(attempts, 1):
                time.sleep(max(float(out.get("retry_after_s") or 0.0),
                               0.01))
        assert last is not None
        raise WireFrameError("rejected", last.get("reason", ""),
                             fatal=False)

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for fd in self._fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds = []
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                pass
            self._mm = None

    def __enter__(self) -> "ShmClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
