"""Execution-runtime services: fault tolerance, watchdogs, snapshot/resume,
atomic model publish/subscribe, and the continuous-training service loop.

This package holds the machinery that keeps long runs alive on flaky
platforms — it deliberately imports neither jax nor any other heavy
dependency at module scope, so the hermetic dryrun bootstrap and the CLI
entry can use it before (or instead of) binding an accelerator platform.
(`continuous` and `serving` are not imported here: they pull numpy and,
lazily, the model stack; import them explicitly where a service loop or
a serving runtime is actually being run.)
"""
from . import publish  # noqa: F401
from . import resilience  # noqa: F401
from . import telemetry  # noqa: F401
from . import tracing  # noqa: F401
from . import warmup  # noqa: F401
from . import xla_obs  # noqa: F401

#: the observability surface (ISSUE 9): `from lightgbm_tpu.runtime import
#: obs` is the supported spelling for metrics/span/exporter access —
#: obs.REGISTRY, obs.span(...), obs.start_http_server(...),
#: obs.METRIC_TABLE.
obs = telemetry

__all__ = ["resilience", "publish", "telemetry", "obs", "tracing",
           "warmup", "xla_obs"]
