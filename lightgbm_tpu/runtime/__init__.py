"""Execution-runtime services: fault tolerance, watchdogs, snapshot/resume.

This package holds the machinery that keeps long runs alive on flaky
platforms — it deliberately imports neither jax nor any other heavy
dependency at module scope, so the hermetic dryrun bootstrap and the CLI
entry can use it before (or instead of) binding an accelerator platform.
"""
from . import resilience  # noqa: F401

__all__ = ["resilience"]
