"""Model-quality firewall: ingest quarantine + pre-publish eval gate.

ISSUE 12: every fault the runtime defended against before this module was
*mechanical* — process death, torn writes, device hangs.  The failure
mode that actually dominates production GBDT systems is **bad data
producing a bad model that gets published and served**.  This module is
stages one and two of the three-stage defense (stage three — canary
routing + automatic rollback — lives in `runtime/policy.CanaryPolicy` +
`runtime/serving.py`):

* **Ingest quarantine** (`validate_rows` + `QuarantineLedger`): every
  parsed or pushed row is validated against the dataset's declared
  schema — non-finite labels, non-finite weights, out-of-range query
  ids, column-count drift — and offenders are routed to a BOUNDED
  ledger (count + a few sample rows + reason) instead of poisoning the
  training window.  Counts land in
  ``lgbm_ingest_quarantined_total{reason}`` and in the cycle's stage
  trail; a configurable quarantine-fraction threshold raises
  `QuarantineExceeded` so a cycle fails loudly rather than training on
  garbage.
* **Pre-publish eval gate** (`holdout_mask` + `evaluate_model` +
  `gate_verdict`): each cycle holds out a DETERMINISTIC slice of the
  window (pure index arithmetic — same window ⇒ same holdout ⇒ same
  verdict, pinned), evaluates the candidate with the existing metric
  stack (`lightgbm_tpu.metric`, the layer the reference grew for
  exactly this purpose — SURVEY §1 L7), and refuses to publish a
  generation whose primary metric regresses beyond
  ``publish_gate_tolerance`` vs the incumbent.  Verdicts land in
  ``lgbm_publish_gate_total{verdict}``; a rejection persists the
  rejected model WITH both metric sets next to the publish dir
  (`runtime/publish.ModelPublisher.record_rejection`) so the decision
  is auditable after the fact.

Everything here is host-side numpy — no jax at module scope, so the
ingest producer thread and test pollers can use it without binding a
platform.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import LightGBMError
from . import telemetry

__all__ = ["QuarantineLedger", "QuarantineExceeded", "validate_rows",
           "holdout_mask", "evaluate_model", "gate_verdict"]

#: quarantine reasons, in the order they are checked; a row failing
#: several checks is counted once under the FIRST failing reason
QUARANTINE_REASONS = ("nonfinite_label", "nonfinite_weight",
                     "bad_query_id", "column_drift")

#: sample rows retained per reason — the ledger is evidence, not a copy
#: of the poison stream
_MAX_SAMPLES = 4


class QuarantineExceeded(LightGBMError):
    """The quarantined fraction of one ingest pass crossed the configured
    threshold: the window is mostly garbage and training on the remainder
    would launder a data outage into a published model.  The continuous
    trainer fails the CYCLE on this (status=quarantine in
    ``lgbm_online_cycles_total``) and retries at the next slot."""


class QuarantineLedger:
    """Bounded record of everything quarantine dropped.

    ``counts`` accumulates per reason; ``samples`` keeps at most
    `_MAX_SAMPLES` (row_repr, reason) pairs per reason so a post-mortem
    can see WHAT was dropped without the ledger growing with the
    outage.  Mirrored into ``lgbm_ingest_quarantined_total{reason}`` at
    every `record`.
    """

    def __init__(self):
        self.counts: Dict[str, int] = {}
        self.samples: Dict[str, List[str]] = {}
        self.rows_seen = 0
        self.rows_quarantined = 0

    def record(self, reason: str, count: int,
               sample_rows: Optional[List[str]] = None) -> None:
        if count <= 0:
            return
        self.counts[reason] = self.counts.get(reason, 0) + int(count)
        self.rows_quarantined += int(count)
        slot = self.samples.setdefault(reason, [])
        for s in (sample_rows or [])[: max(_MAX_SAMPLES - len(slot), 0)]:
            slot.append(s)
        telemetry.counter("lgbm_ingest_quarantined_total").inc(
            int(count), reason=reason)

    def observe_clean(self, count: int) -> None:
        self.rows_seen += int(count)

    @property
    def total(self) -> int:
        return self.rows_quarantined

    def fraction(self) -> float:
        denom = self.rows_seen + self.rows_quarantined
        return self.rows_quarantined / denom if denom else 0.0

    def summary(self) -> Dict[str, Any]:
        """The stage-trail / artifact record."""
        return {"quarantined_total": self.rows_quarantined,
                "rows_seen": self.rows_seen,
                "by_reason": dict(self.counts),
                "samples": {r: list(s) for r, s in self.samples.items()}}


def _sample_reprs(X: np.ndarray, y: Optional[np.ndarray],
                  idx: np.ndarray) -> List[str]:
    out = []
    for i in idx[:_MAX_SAMPLES]:
        lab = "?" if y is None else repr(float(y[i]))
        head = np.asarray(X[i]).ravel()[:6]
        out.append("row[%d] label=%s X[:6]=%s" % (int(i), lab,
                                                  np.array2string(head)))
    return out


def validate_rows(X: np.ndarray, y: Optional[np.ndarray] = None,
                  weight: Optional[np.ndarray] = None,
                  query: Optional[np.ndarray] = None,
                  expected_features: Optional[int] = None,
                  ledger: Optional[QuarantineLedger] = None
                  ) -> Tuple[np.ndarray, Dict[str, int]]:
    """Schema validation of one parsed/pushed chunk.

    Returns ``(keep_mask, counts)``: a boolean mask of rows safe to
    train on, and the per-reason quarantine counts.  Checks, in order:

    * **column_drift** — the chunk's width differs from the declared
      feature count: the WHOLE chunk is quarantined (rows of the wrong
      shape cannot be partially salvaged);
    * **nonfinite_label** — NaN/Inf labels (a NaN gradient is how one
      bad logging row poisons every histogram it touches);
    * **nonfinite_weight** — NaN/Inf weights;
    * **bad_query_id** — non-finite or negative query ids in ranking
      mode (group boundaries derived from them would be garbage).

    NaN *features* are deliberately NOT quarantined: missing values are
    first-class GBDT inputs (`use_missing`, SURVEY §2.2) and dropping
    them would silently change models on legitimate data.
    """
    n = int(X.shape[0])
    keep = np.ones(n, dtype=bool)
    counts: Dict[str, int] = {}
    if n == 0:
        return keep, counts

    if expected_features is not None and int(X.shape[1]) != int(
            expected_features):
        counts["column_drift"] = n
        if ledger is not None:
            ledger.record("column_drift", n, [
                "chunk width %d != declared %d"
                % (X.shape[1], expected_features)])
        return np.zeros(n, dtype=bool), counts

    def _apply(mask_bad: np.ndarray, reason: str) -> None:
        bad = mask_bad & keep
        c = int(bad.sum())
        if not c:
            return
        counts[reason] = c
        if ledger is not None:
            ledger.record(reason, c,
                          _sample_reprs(X, y, np.flatnonzero(bad)))
        keep[bad] = False

    if y is not None:
        yv = np.asarray(y, dtype=np.float64).reshape(-1)
        _apply(~np.isfinite(yv), "nonfinite_label")
    if weight is not None:
        wv = np.asarray(weight, dtype=np.float64).reshape(-1)
        _apply(~np.isfinite(wv), "nonfinite_weight")
    if query is not None:
        qv = np.asarray(query, dtype=np.float64).reshape(-1)
        _apply(~np.isfinite(qv) | (qv < 0), "bad_query_id")
    if ledger is not None:
        ledger.observe_clean(int(keep.sum()))
    return keep, counts


# ---------------------------------------------------------------------------
# pre-publish eval gate
# ---------------------------------------------------------------------------

def holdout_mask(n_rows: int, holdout_frac: float,
                 query: Optional[np.ndarray] = None) -> np.ndarray:
    """Deterministic holdout selection: pure index arithmetic, no RNG —
    the same window always yields the same mask (the gate-determinism
    pin).  Every ``k``-th row (``k = round(1/holdout_frac)``) is held
    out; in ranking mode every ``k``-th QUERY GROUP is held out instead,
    so a group is never torn between train and holdout."""
    n = int(n_rows)
    if n <= 0 or holdout_frac <= 0.0:
        return np.zeros(n, dtype=bool)
    k = max(int(round(1.0 / min(holdout_frac, 0.5))), 2)
    if query is None:
        mask = (np.arange(n) % k) == (k - 1)
    else:
        q = np.asarray(query).reshape(-1)
        starts = np.concatenate([[0], np.flatnonzero(np.diff(q)) + 1])
        group_of = np.searchsorted(starts, np.arange(n), side="right") - 1
        mask = (group_of % k) == (k - 1)
    if mask.all() or not mask.any():
        # degenerate tiny windows: never hold out everything (or nothing
        # when a fraction was asked for) — fall back to the last row
        mask = np.zeros(n, dtype=bool)
        mask[-1] = True
    return mask


def evaluate_model(model, X: np.ndarray, y: np.ndarray, params: Dict,
                   weight: Optional[np.ndarray] = None,
                   query: Optional[np.ndarray] = None
                   ) -> List[Tuple[str, float, bool]]:
    """Metric-stack evaluation of one model on a holdout slice:
    ``[(metric_name, value, is_higher_better), ...]`` using the SAME
    metric layer training uses (config-selected metrics, objective
    transform applied by each metric).  `model` is a `GBDTModel` or
    anything with ``predict_raw``."""
    from ..config import Config
    from ..metric import create_metrics
    from ..objective import create_objective

    cfg = Config(dict(params))
    objective = create_objective(cfg.objective, cfg) \
        if isinstance(cfg.objective, str) else None
    raw = np.asarray(model.predict_raw(np.asarray(X, dtype=np.float64))).T
    qb = None
    if query is not None and len(query):
        q = np.asarray(query).reshape(-1)
        starts = np.concatenate([[0], np.flatnonzero(np.diff(q)) + 1,
                                 [q.size]])
        qb = starts.astype(np.int64)
    out: List[Tuple[str, float, bool]] = []
    for m in create_metrics(cfg.metric, cfg):
        m.init(np.asarray(y, dtype=np.float64), weight, qb)
        score = raw if getattr(m, "multiclass", False) else \
            (raw[0] if raw.shape[0] == 1 else raw.reshape(-1))
        out.append((m.name, float(m.eval(score, objective)),
                    bool(m.is_higher_better)))
    return out


def gate_verdict(candidate: List[Tuple[str, float, bool]],
                 incumbent: Optional[List[Tuple[str, float, bool]]],
                 tolerance: float,
                 primary_metric: Optional[str] = None) -> Dict[str, Any]:
    """The gate decision over two metric sets.

    The PRIMARY metric (named, or the first evaluated one) drives the
    verdict: the candidate is rejected when it regresses more than
    ``tolerance`` RELATIVE to the incumbent's value (direction taken
    from the metric's higher-is-better flag).  ``tolerance=inf``
    disables the gate entirely (the default-off contract: disabled, the
    trainer behaves byte-identically to a gate-less build).  Returns the
    auditable record that lands in the publish meta / rejection file and
    in ``lgbm_publish_gate_total{verdict}``."""
    rec: Dict[str, Any] = {
        "tolerance": None if math.isinf(tolerance) else float(tolerance),
        "candidate": [[n, v, h] for n, v, h in candidate],
        "incumbent": None if incumbent is None
        else [[n, v, h] for n, v, h in incumbent],
    }
    if math.isinf(tolerance):
        rec.update(verdict="disabled", regression=None)
        return rec
    if not candidate:
        # no metric configured: nothing to gate on — pass, loudly noted
        rec.update(verdict="no_metric", regression=None)
        return rec
    pick = 0
    if primary_metric:
        for i, (n, _, _) in enumerate(candidate):
            if n == primary_metric:
                pick = i
                break
        else:
            raise LightGBMError(
                "publish_gate_metric %r is not among the evaluated "
                "metrics %r" % (primary_metric,
                                [n for n, _, _ in candidate]))
    name, cand_v, higher = candidate[pick]
    rec["metric"] = name
    if incumbent is None:
        rec.update(verdict="no_incumbent", regression=None)
        return rec
    inc_v = None
    for n, v, _ in incumbent:
        if n == name:
            inc_v = v
            break
    if inc_v is None or not math.isfinite(inc_v):
        rec.update(verdict="no_incumbent", regression=None)
        return rec
    # signed regression: positive = candidate is WORSE, relative to the
    # incumbent's magnitude (floored so a near-zero incumbent loss does
    # not turn numeric noise into an infinite relative regression)
    delta = (inc_v - cand_v) if higher else (cand_v - inc_v)
    regression = delta / max(abs(inc_v), 1e-12)
    rec["regression"] = float(regression)
    rec["verdict"] = "reject" if (math.isfinite(cand_v) is False
                                  or regression > tolerance) else "pass"
    return rec
