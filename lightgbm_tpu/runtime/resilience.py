"""Fault-tolerant execution runtime.

The reference survives multi-hour training through periodic snapshots
(gbdt.cpp:330-334) and bounded socket timeouts (linkers_socket.cpp); this
module is the TPU-native equivalent of that posture, hardened against the
failures that actually hit this repo (five consecutive rounds of red
MULTICHIP artifacts: rc=124, hung after "import jax" on a dead axon
tunnel, zero diagnostics):

* **Stage watchdog** (`Watchdog`): every dryrun/bench/ingest stage runs
  under a named deadline.  On expiry the watchdog captures `faulthandler`
  tracebacks of ALL threads, persists the stage trail + culprit into a
  JSON report, and either raises `StageTimeout` (soft mode, host
  processes) or kills the process group with a distinctive exit code
  (hard mode, disposable subprocesses) — a hang can never again surface
  as a bare rc=124.

* **Platform health probe + degradation chain** (`probe_platform`,
  `resolve_backend`): backend init is probed in a short-deadline
  subprocess (the probe child dumps its own tracebacks via
  `faulthandler.dump_traceback_later` before the parent's kill lands),
  retried with jittered backoff, then degraded to cpu with a
  machine-readable `degradation_event`.

* **Preemption-safe snapshots** (`write_snapshot`, `find_resume_snapshot`,
  `restore_training_state`, `PreemptionGuard`): snapshot files are model
  files plus a footer carrying the full training state (scores, payload
  row order, RNG streams, variant bookkeeping) and a sha256 checksum;
  writes are atomic (tmp + fsync + rename) with keep-last-K retention;
  SIGTERM/SIGINT write a final snapshot at the next iteration boundary;
  resume scans past corrupt snapshots to the newest valid one and
  continues to a model byte-identical to an uninterrupted run.

* **Non-finite sentinel** (`NonFiniteDetected`, `SentinelGuard`): tree
  outputs fetched from device every iteration are screened for NaN/inf
  under `sentinel_nonfinite=abort|rollback`.

* **Fault injection** (`LGBM_TPU_FAULT`): every behavior above is
  testable through environment-injected faults, e.g.
  ``LGBM_TPU_FAULT=hang_import:30,die_at_iter:7,corrupt_snapshot,nan_grad:5``.
  See docs/RESILIENCE.md for the full matrix.

No jax / numpy import at module scope: the hermetic dryrun bootstrap and
the CLI entry must be able to use this module without binding a platform.
"""
from __future__ import annotations

import base64
import contextlib
import datetime
import hashlib
import io
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "StageTimeout", "Watchdog", "wallclock",
    "probe_platform", "resolve_backend", "backoff_delays",
    "atomic_write", "read_stage_report", "write_snapshot",
    "validate_snapshot",
    "load_snapshot_state", "find_resume_snapshot", "snapshot_paths",
    "capture_training_state", "restore_training_state",
    "make_resume_callback", "PreemptionGuard", "TrainingPreempted",
    "NonFiniteDetected", "SentinelGuard",
    "fault_arg", "fault_active", "maybe_die_or_preempt",
    "maybe_probe_hang_seconds", "maybe_corrupt_snapshot",
    "maybe_inject_nan", "maybe_slow_stage", "maybe_torn_publish",
    "maybe_die_at_publish", "maybe_die_at_spawn", "maybe_die_at_ring",
    "maybe_fail_predict", "DevicePredictFault",
    "maybe_poison_rows", "maybe_flip_labels", "maybe_regress_model",
    "snapshot_model_text", "FAULT_TABLE", "FAULT_NAMES",
]


def wallclock() -> str:
    """ISO-ish wall-clock tag: every stage line of a red artifact must
    show WHEN it started, so a stall's duration is readable from the
    trail alone."""
    return datetime.datetime.now().strftime("%Y-%m-%dT%H:%M:%S")


# ---------------------------------------------------------------------------
# fault injection (LGBM_TPU_FAULT=name[:arg],name[:arg],...)
# ---------------------------------------------------------------------------

#: THE fault registry: every recognized fault point, with its argument
#: spelling and injection point.  This table is the single source of
#: truth shared by the parser below and the docs/RESILIENCE.md injection
#: matrix (test-pinned against each other, so the table and the parser
#: cannot drift).  Anything else in the spec is rejected loudly — a
#: typoed fault name silently injecting nothing would make a "green
#: under fault" test meaningless.
FAULT_TABLE: Dict[str, Dict[str, str]] = {
    "hang_import": {
        "arg": "SECS",
        "injects_at": "platform probe child (probe_platform), "
                      "non-cpu binds only"},
    "die_at_iter": {
        "arg": "K",
        "injects_at": "Booster.update entry (maybe_die_or_preempt)"},
    "sigterm_at_iter": {
        "arg": "K",
        "injects_at": "Booster.update entry (SIGTERM to self)"},
    "corrupt_snapshot": {
        "arg": "[K]",
        "injects_at": "write_snapshot, after the atomic rename"},
    "nan_grad": {
        "arg": "K",
        "injects_at": "the _finish_tree host fetch (sentinel_check)"},
    "bogus_platform": {
        "arg": "",
        "injects_at": "probe_platform / resolve_backend request rewrite"},
    "torn_write": {
        "arg": "[K]",
        "injects_at": "ModelPublisher.publish, before the atomic path"},
    "slow_stage": {
        "arg": "NAME:SECS",
        "injects_at": "stage open in the continuous trainer "
                      "(maybe_slow_stage; one-shot per process)"},
    "die_at_publish": {
        "arg": "K",
        "injects_at": "ModelPublisher.publish, between generation rename "
                      "and manifest write"},
    "die_at_predict": {
        "arg": "K",
        "injects_at": "device-predict micro-batch boundary "
                      "(maybe_fail_predict in DevicePredictor.predict_raw)"},
    "slow_predict": {
        "arg": "SECS",
        "injects_at": "device-predict micro-batch boundary "
                      "(maybe_fail_predict; every batch while armed)"},
    "poison_rows": {
        "arg": "F",
        "injects_at": "online ingest, after parse / before quarantine "
                      "(maybe_poison_rows; fraction F of every chunk)"},
    "label_flip": {
        "arg": "K",
        "injects_at": "online cycle K's training-window labels "
                      "(maybe_flip_labels in the continuous trainer)"},
    "regress_model": {
        "arg": "K",
        "injects_at": "continuous trainer's publish seam, AFTER the "
                      "eval gate (maybe_regress_model on cycle K's "
                      "model text)"},
    "die_at_spawn": {
        "arg": "K",
        "injects_at": "ServingRuntime.start, after the prewarm pass and "
                      "BEFORE /healthz readiness (maybe_die_at_spawn on "
                      "the K-th fleet spawn ordinal)"},
    "die_at_ring": {
        "arg": "K",
        "injects_at": "ShmClient ring produce, right after the K-th "
                      "request frame is published with its response "
                      "unread (maybe_die_at_ring) — the crashed-client "
                      "reclamation path"},
}

FAULT_NAMES = tuple(FAULT_TABLE)


def _fault_spec() -> Dict[str, Optional[str]]:
    """Parse LGBM_TPU_FAULT on every call (cheap, and lets tests flip the
    environment without any cache-busting protocol)."""
    raw = os.environ.get("LGBM_TPU_FAULT", "")
    if not raw:
        return {}
    out: Dict[str, Optional[str]] = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, _, arg = tok.partition(":")
        if name not in FAULT_NAMES:
            raise ValueError(
                "unknown fault %r in LGBM_TPU_FAULT=%r (known: %s)"
                % (name, raw, ", ".join(FAULT_NAMES)))
        out[name] = arg if arg != "" else None
    return out


def fault_active(name: str) -> bool:
    return name in _fault_spec()


def fault_arg(name: str, default: Optional[str] = None) -> Optional[str]:
    spec = _fault_spec()
    if name not in spec:
        return default
    return spec[name] if spec[name] is not None else default


def maybe_probe_hang_seconds(platform: Optional[str]) -> float:
    """`hang_import:SECS` models the dead-tunnel failure: binding a
    non-cpu platform hangs inside `import jax` / device init.  The cpu
    platform never hangs — that is exactly why the degradation chain
    lands there — so the injection only applies to non-cpu probes."""
    if platform is None or platform == "cpu":
        return 0.0
    if not fault_active("hang_import"):
        return 0.0
    return float(fault_arg("hang_import", "30"))


def maybe_die_or_preempt(booster) -> None:
    """Training-loop fault hooks, called at every iteration boundary
    (Booster.update entry):

    * ``die_at_iter:K`` — an abrupt, snapshot-less death (power loss /
      OOM-killer model) once K iterations are complete: `os._exit(137)`.
    * ``sigterm_at_iter:K`` — a graceful preemption notice: SIGTERM is
      delivered to this process, which the PreemptionGuard turns into
      write-final-snapshot-then-exit at the iteration boundary.
    """
    spec = _fault_spec()
    if "die_at_iter" not in spec and "sigterm_at_iter" not in spec:
        return
    eng = getattr(booster, "_engine", None)
    if eng is None:
        return
    # an armed fault counts COMPLETED iterations: drain the dispatch
    # pipeline so the count (and the state a die/preempt leaves behind)
    # is the synchronous loop's
    eng.flush()
    done = int(eng.model.current_iteration)
    if "die_at_iter" in spec and done >= int(spec["die_at_iter"] or 0):
        sys.stderr.write("[%s] FAULT die_at_iter: abrupt exit after %d "
                         "iterations\n" % (wallclock(), done))
        sys.stderr.flush()
        os._exit(137)
    if "sigterm_at_iter" in spec and done == int(spec["sigterm_at_iter"] or 0):
        sys.stderr.write("[%s] FAULT sigterm_at_iter: delivering SIGTERM "
                         "after %d iterations\n" % (wallclock(), done))
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_corrupt_snapshot(path: str, total_iter: int) -> None:
    """`corrupt_snapshot[:K]` truncates the snapshot written at iteration
    K (every snapshot when K is omitted) AFTER the atomic rename —
    modeling a snapshot that landed on disk torn (e.g. the filesystem
    died mid-durability).  Resume must detect it via the checksum and
    fall back to the previous valid snapshot."""
    if not fault_active("corrupt_snapshot"):
        return
    arg = fault_arg("corrupt_snapshot")
    if arg is not None and int(arg) != int(total_iter):
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(size // 2, 1))
    sys.stderr.write("[%s] FAULT corrupt_snapshot: truncated %s to %d "
                     "bytes\n" % (wallclock(), path, max(size // 2, 1)))


def maybe_inject_nan(engine, host: Dict) -> None:
    """`nan_grad:K` poisons iteration K's fetched tree outputs the way a
    non-finite gradient burst would (NaN grads -> NaN histogram sums ->
    NaN leaf values) so the sentinel's detection + policy machinery is
    exercised end-to-end."""
    if not fault_active("nan_grad"):
        return
    if int(engine.iter) != int(fault_arg("nan_grad", "0")):
        return
    host["leaf_value"] = host["leaf_value"].copy()
    host["leaf_value"][:] = float("nan")


#: stages already stalled by `slow_stage` this process — the injection is
#: one-shot per process (it models a transient stall, e.g. a filesystem
#: hiccup; a permanent stall would just crash-loop the service and prove
#: nothing about recovery).
_SLOW_STAGES_FIRED: set = set()


def maybe_slow_stage(stage_name: str, defer: bool = False) -> float:
    """`slow_stage:NAME:SECS` stalls the first stage whose name contains
    NAME for SECS seconds — long enough to blow the stage's watchdog
    deadline, which is the point: the service must surface the timeout in
    the stage trail and carry on with the next cycle.  Returns the
    injected stall (0.0 when nothing fired); `defer=True` skips the sleep
    so the caller can record the injection in its stage trail FIRST (the
    watchdog alarm lands mid-sleep, after which nothing else runs)."""
    if not fault_active("slow_stage"):
        return 0.0
    arg = fault_arg("slow_stage", "")
    name, _, secs = (arg or "").partition(":")
    if not name or name not in stage_name or name in _SLOW_STAGES_FIRED:
        return 0.0
    _SLOW_STAGES_FIRED.add(name)
    stall = float(secs or "5")
    sys.stderr.write("[%s] FAULT slow_stage: stalling stage %r for %.1fs\n"
                     % (wallclock(), stage_name, stall))
    sys.stderr.flush()
    if not defer:
        time.sleep(stall)
    return stall


def maybe_torn_publish(path: str, body: str, publish_count: int) -> None:
    """`torn_write[:K]` models a publisher whose K-th publish (1-based;
    every publish when K is omitted) lands TORN on disk and whose process
    dies before it can repair anything: half the body is written straight
    to the FINAL path (no tmp, no fsync, no rename — exactly the
    non-atomic write the real publisher never performs) and the process
    exits abruptly.  Subscribers must reject the torn generation via its
    checksum; the relaunched publisher must republish it."""
    if not fault_active("torn_write"):
        return
    arg = fault_arg("torn_write")
    if arg is not None and int(arg) != int(publish_count):
        return
    with open(path, "w") as fh:
        fh.write(body[: max(len(body) // 2, 1)])
    sys.stderr.write("[%s] FAULT torn_write: tore publish #%d at %s and "
                     "dying\n" % (wallclock(), publish_count, path))
    sys.stderr.flush()
    os._exit(137)


def maybe_die_at_publish(publish_count: int) -> None:
    """`die_at_publish:K` kills the process BETWEEN the generation file's
    atomic rename and the manifest update of the K-th publish (1-based) —
    the window where the newest valid generation on disk is ahead of the
    manifest pointer.  Subscribers must still resolve a valid model and
    the relaunched publisher must reconcile."""
    if not fault_active("die_at_publish"):
        return
    if int(fault_arg("die_at_publish", "1")) != int(publish_count):
        return
    sys.stderr.write("[%s] FAULT die_at_publish: abrupt exit mid-publish "
                     "#%d (generation renamed, manifest stale)\n"
                     % (wallclock(), publish_count))
    sys.stderr.flush()
    os._exit(137)


def maybe_die_at_spawn(spawn_ordinal: Optional[int] = None) -> None:
    """`die_at_spawn:K` kills a serving replica AFTER its prewarm pass and
    BEFORE /healthz flips ready (ISSUE 17) — the window where a fleet
    controller has paid the spawn cost but admitted no traffic.  The
    controller must detect the dead child and relaunch without ever
    routing to it.

    ``spawn_ordinal`` is the fleet-wide 1-based spawn sequence number,
    normally delivered by the spawner through ``LGBM_TPU_SPAWN_ORDINAL``
    (each replica is a fresh process, so a process-local counter could
    never reach K > 1)."""
    if not fault_active("die_at_spawn"):
        return
    if spawn_ordinal is None:
        try:
            spawn_ordinal = int(os.environ.get("LGBM_TPU_SPAWN_ORDINAL",
                                               "1") or 1)
        except ValueError:
            spawn_ordinal = 1
    if int(fault_arg("die_at_spawn", "1")) != int(spawn_ordinal):
        return
    sys.stderr.write("[%s] FAULT die_at_spawn: abrupt exit during spawn "
                     "#%d (prewarmed, never ready)\n"
                     % (wallclock(), spawn_ordinal))
    sys.stderr.flush()
    os._exit(137)


def maybe_die_at_ring(frames_in_flight: int) -> None:
    """`die_at_ring:K` kills an SHM ring client the instant its K-th
    request frame is PUBLISHED with the response still unread (ISSUE 20)
    — the worst reclamation case: the server holds a mapped segment with
    live admissions aliasing it and a peer that will never drain the
    response ring.  The server must detect the death on the control
    socket, drain the in-flight work, unmap with zero leaked mappings
    and keep every other client byte-verified."""
    if not fault_active("die_at_ring"):
        return
    if int(fault_arg("die_at_ring", "1")) != int(frames_in_flight):
        return
    sys.stderr.write("[%s] FAULT die_at_ring: abrupt client exit with "
                     "%d frames in flight in the ring\n"
                     % (wallclock(), frames_in_flight))
    sys.stderr.flush()
    os._exit(137)


#: device-predict fault bookkeeping: batches seen while die_at_predict is
#: armed (the victim is the predict CALL, never the process — a serving
#: runtime must survive device loss, which is the point of the injection)
_PREDICT_FAULT = {"batches": 0}


class DevicePredictFault(RuntimeError):
    """The injected stand-in for an XLA device failure mid-predict
    (`LGBM_TPU_FAULT=die_at_predict`): the serving runtime must catch it,
    trip its circuit breaker, and answer from the host predictor."""


def maybe_fail_predict() -> None:
    """Serving fault seam, consulted at every device-predict micro-batch
    boundary (models/device_predictor.py predict_raw):

    * ``slow_predict:SECS`` — stalls EVERY device batch by SECS while
      armed (a degraded device, cleared by clearing the env var); long
      enough to blow the serving runtime's predict deadline, which is
      the point: the batch must be re-served from the host path and the
      timeout must land in the serving stage trail.
    * ``die_at_predict:K`` — the K-th device batch (1-based, counted
      while armed) and every later one raise `DevicePredictFault`; the
      serving runtime must degrade to the host predictor and recover to
      the device path once the fault clears.
    """
    spec = _fault_spec()
    if "slow_predict" in spec:
        stall = float(spec["slow_predict"] or "5")
        sys.stderr.write("[%s] FAULT slow_predict: stalling device batch "
                         "for %.1fs\n" % (wallclock(), stall))
        sys.stderr.flush()
        time.sleep(stall)
    if "die_at_predict" in spec:
        _PREDICT_FAULT["batches"] += 1
        if _PREDICT_FAULT["batches"] >= int(spec["die_at_predict"] or "1"):
            sys.stderr.write("[%s] FAULT die_at_predict: failing device "
                             "batch #%d\n"
                             % (wallclock(), _PREDICT_FAULT["batches"]))
            sys.stderr.flush()
            raise DevicePredictFault(
                "injected device predict failure "
                "(LGBM_TPU_FAULT=die_at_predict, batch #%d)"
                % _PREDICT_FAULT["batches"])


def maybe_poison_rows(X, y):
    """`poison_rows:F` corrupts fraction F of every parsed ingest chunk
    the way an upstream logging outage would: a deterministic stride of
    rows gets a non-finite label (alternating NaN / +inf so both spellings
    are exercised).  The quarantine (ISSUE 12 stage one) must route every
    poisoned row to the ledger — a single NaN label reaching a histogram
    poisons every split under it.  Returns (y, n_poisoned); X is
    returned untouched (NaN FEATURES are legitimate missing values and
    are deliberately not part of this fault)."""
    if not fault_active("poison_rows") or y is None or len(y) == 0:
        return y, 0
    frac = float(fault_arg("poison_rows", "0.1"))
    if frac <= 0:
        return y, 0
    stride = max(int(round(1.0 / min(frac, 1.0))), 1)
    import numpy as np
    y = np.array(y, dtype=np.float64, copy=True)
    idx = np.arange(0, len(y), stride)
    y[idx[0::2]] = float("nan")
    y[idx[1::2]] = float("inf")
    sys.stderr.write("[%s] FAULT poison_rows: poisoned %d/%d labels\n"
                     % (wallclock(), len(idx), len(y)))
    sys.stderr.flush()
    return y, int(len(idx))


def maybe_flip_labels(y, cycle: int):
    """`label_flip:K` inverts the training labels of cycle K's window —
    valid-looking values carrying wrong information, the data bug the
    ingest quarantine CANNOT catch (every row passes schema validation).
    The pre-publish eval gate (ISSUE 12 stage two) is the defense: the
    model trained on flipped labels regresses on the holdout and must
    not be published.  Returns (y, flipped?)."""
    if not fault_active("label_flip") or y is None or len(y) == 0:
        return y, False
    if int(fault_arg("label_flip", "0")) != int(cycle):
        return y, False
    import numpy as np
    y = np.asarray(y, dtype=np.float64)
    flipped = (float(np.max(y)) + float(np.min(y))) - y
    sys.stderr.write("[%s] FAULT label_flip: inverted cycle %d's %d "
                     "labels\n" % (wallclock(), cycle, len(y)))
    sys.stderr.flush()
    return flipped, True


def maybe_regress_model(model_text: str, cycle: int) -> str:
    """`regress_model:K` sabotages cycle K's model text at the publish
    seam, AFTER the eval gate has judged the (clean) candidate — the
    regression the offline gate cannot see and only the serving canary
    (ISSUE 12 stage three) can catch.  Every `leaf_value=` line is
    rescaled by -2, so the published generation is a VALID, loadable
    model whose live predictions are badly wrong.  The canary must roll
    the fleet back to the prior generation."""
    if not fault_active("regress_model"):
        return model_text
    if int(fault_arg("regress_model", "0")) != int(cycle):
        return model_text
    lines = model_text.split("\n")
    for i, line in enumerate(lines):
        if line.startswith("leaf_value="):
            vals = ["%.17g" % (-2.0 * float(tok))
                    for tok in line[len("leaf_value="):].split()]
            lines[i] = "leaf_value=" + " ".join(vals)
    sys.stderr.write("[%s] FAULT regress_model: sabotaged cycle %d's "
                     "leaf values at the publish seam\n"
                     % (wallclock(), cycle))
    sys.stderr.flush()
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# stage watchdog
# ---------------------------------------------------------------------------

class StageTimeout(RuntimeError):
    """A watchdogged stage exceeded its deadline (soft mode)."""

    def __init__(self, stage: str, seconds: float):
        super().__init__("stage %r exceeded its %ds deadline"
                         % (stage, seconds))
        self.stage = stage
        self.seconds = seconds


#: hard-mode exit code.  Deliberately NOT 124 (the driver's bare-timeout
#: code): rc 73 means "the stage watchdog fired and the diagnostics are in
#: the stage report / stderr", never "something hung silently".
WATCHDOG_EXIT_CODE = 73


def _dump_all_threads() -> str:
    """faulthandler tracebacks of every thread, as text."""
    import faulthandler
    with tempfile.TemporaryFile(mode="w+") as fh:
        faulthandler.dump_traceback(file=fh, all_threads=True)
        fh.seek(0)
        return fh.read()


class Watchdog:
    """Per-stage SIGALRM watchdog with a persistent stage trail.

    ``wd(name)`` (or ``wd.stage(name, seconds)``) opens a named stage
    under a deadline; a hung stage prints its name, dumps faulthandler
    tracebacks of all threads, rewrites the JSON report (when
    ``report_path`` is set) and then either raises `StageTimeout`
    (``hard=False`` — host processes, where killing the interpreter would
    kill the DRIVER) or kills the whole process group with
    `WATCHDOG_EXIT_CODE` (``hard=True`` — disposable subprocesses).

    The report is rewritten at every stage TRANSITION too, so even a
    SIGKILL'd process leaves a trail naming the stage it died in.

    **Thread mode** (`use_alarm=False`, auto-selected off the main
    thread): SIGALRM cannot be armed outside the main thread, so the
    watchdog keeps only the trail bookkeeping and the OWNER enforces
    deadlines itself (e.g. a bounded queue wait), reporting expiries via
    `record_timeout()` — same trail semantics as a fired alarm (stage
    closed as timeout, all-thread tracebacks captured, report persisted)
    but it never raises or exits.  `keep_last=N` bounds the trail for
    long-lived owners (a serving runtime opening one stage per batch
    must not grow its flight recorder without bound); dropped entries
    are counted in the report.
    """

    def __init__(self, seconds: int, hard: bool = False,
                 report_path: Optional[str] = None,
                 kill_process_group: bool = False,
                 label: str = "stage", stream=None,
                 use_alarm: Optional[bool] = None,
                 keep_last: Optional[int] = None):
        self.seconds = int(seconds)
        self.hard = hard
        self.report_path = report_path or os.environ.get(
            "LGBM_TPU_STAGE_REPORT")
        self.kill_process_group = kill_process_group
        self.label = label
        self.stream = stream  # None -> sys.stdout at emit time
        if use_alarm is None:
            use_alarm = (hasattr(signal, "SIGALRM") and threading
                         .current_thread() is threading.main_thread())
        self.use_alarm = bool(use_alarm)
        self.keep_last = keep_last
        self.dropped_stages = 0
        self.stage = "<init>"
        self.stages: List[Dict[str, Any]] = []
        self.tracebacks: Optional[str] = None
        self._t0: Optional[float] = None

    # -- trail bookkeeping ---------------------------------------------------
    def _close_current(self, status: str) -> None:
        if self._t0 is not None and self.stages:
            dur = round(time.monotonic() - self._t0, 3)
            self.stages[-1]["dur_s"] = dur
            self.stages[-1]["status"] = status
            # every stage close is ALSO a span in the metrics registry
            # (ISSUE 9): stages, spans and scraped metrics share one
            # clock and one naming scheme.  Lazy import — telemetry
            # imports helpers from THIS module at its module scope.
            try:
                from . import telemetry
                telemetry.record_span(
                    "%s/%s" % (self.label, self.stages[-1]["name"]),
                    dur, status=status)
            except Exception:            # noqa: BLE001 — never fatal
                pass
        self._t0 = None

    def report(self) -> Dict[str, Any]:
        rep: Dict[str, Any] = {"stages": self.stages, "culprit": None}
        for st in self.stages:
            if st.get("status") in ("timeout", "running", "error"):
                rep["culprit"] = st["name"]
        if self.dropped_stages:
            rep["dropped_stages"] = self.dropped_stages
        if self.tracebacks is not None:
            rep["tracebacks"] = self.tracebacks
        return rep

    def _persist(self) -> None:
        if not self.report_path:
            return
        try:
            atomic_write(self.report_path,
                         json.dumps(self.report(), indent=1))
        except OSError:
            pass  # report persistence must never take the run down

    # -- stage transitions ---------------------------------------------------
    def __call__(self, stage: str, seconds: Optional[int] = None) -> None:
        """Open `stage` under a deadline (default: the watchdog's),
        closing the previous stage as ok."""
        self._close_current("ok")
        budget = int(seconds if seconds is not None else self.seconds)
        self.stage = stage
        self.stages.append({"name": stage, "t_start": wallclock(),
                            "budget_s": budget, "status": "running"})
        if self.keep_last and len(self.stages) > self.keep_last:
            drop = len(self.stages) - self.keep_last
            del self.stages[:drop]
            self.dropped_stages += drop
        self._t0 = time.monotonic()
        out = self.stream if self.stream is not None else sys.stdout
        out.write("[%s] %s: %s (budget %ds)\n"
                  % (wallclock(), self.label, stage, budget))
        out.flush()
        self._persist()
        if self.use_alarm:
            if budget > 0:
                signal.signal(signal.SIGALRM, self._fire)
                signal.alarm(budget)
            else:
                # an UNBOUNDED stage must disarm the previous stage's
                # alarm — otherwise it fires minutes later and blames
                # this stage for the last one's deadline
                signal.alarm(0)

    def annotate(self, key: str, value: Any) -> None:
        """Attach structured evidence (sync-audit deltas, injected-fault
        notes, publish latencies) to the CURRENT stage's trail entry and
        re-persist — the stage trail is the service's flight recorder, so
        per-stage measurements belong in it, not in a side channel."""
        if self.stages:
            self.stages[-1][key] = value
            self._persist()

    @contextlib.contextmanager
    def stage_scope(self, stage: str, seconds: Optional[int] = None):
        """Context-manager spelling; closes the stage on exit.  The alarm
        is disarmed on EVERY exit path — an armed alarm escaping the
        scope would fire minutes later in unrelated code."""
        self(stage, seconds)
        try:
            yield
        except StageTimeout:
            raise
        except BaseException:
            if self.use_alarm:
                signal.alarm(0)
            self._close_current("error")
            self._persist()
            raise
        else:
            self.done(final=False)

    def _fire(self, signum, frame):
        self._close_current("timeout")
        self.tracebacks = _dump_all_threads()
        msg = ("[%s] WATCHDOG: %s %r exceeded its deadline; thread "
               "tracebacks follow\n%s"
               % (wallclock(), self.label, self.stage, self.tracebacks))
        sys.stderr.write(msg)
        sys.stderr.flush()
        self._persist()
        if self.hard:
            if self.kill_process_group:
                try:
                    # children first (the hang may live in a grandchild);
                    # this process dies of its own SIGKILL last
                    os.killpg(os.getpgid(0), signal.SIGKILL)
                except (OSError, PermissionError):
                    pass
            os._exit(WATCHDOG_EXIT_CODE)
        raise StageTimeout(self.stage, self.stages[-1]["budget_s"]
                           if self.stages else self.seconds)

    def record_timeout(self, note: Optional[str] = None) -> None:
        """Thread-mode deadline expiry: the owner enforced the deadline
        itself (a bounded wait on the batch's completion event, say) and
        reports it here — the CURRENT stage closes as ``timeout`` with
        all-thread tracebacks captured and the report persisted, exactly
        like a fired alarm, but nothing raises and nothing exits (the
        owner is a long-lived server that must carry on)."""
        self._close_current("timeout")
        if note and self.stages:
            self.stages[-1]["note"] = note
        self.tracebacks = _dump_all_threads()
        sys.stderr.write("[%s] WATCHDOG: %s %r exceeded its deadline "
                         "(thread mode)%s\n"
                         % (wallclock(), self.label, self.stage,
                            ": " + note if note else ""))
        sys.stderr.flush()
        self._persist()

    def done(self, final: bool = True) -> None:
        """Disarm the alarm (MUST run before the watchdog owner returns:
        an orphaned SIGALRM would hard-kill the host minutes later)."""
        if self.use_alarm:
            signal.alarm(0)
            if final:
                signal.signal(signal.SIGALRM, signal.SIG_DFL)
        self._close_current("ok")
        if final:
            self._persist()


# ---------------------------------------------------------------------------
# platform health probe + degradation chain
# ---------------------------------------------------------------------------

def backoff_delays(attempts: int, base: float = 1.0, cap: float = 8.0,
                   seed: int = 0) -> List[float]:
    """Deterministic jittered exponential backoff (full-jitter flavour,
    but seeded so tests and multi-process ranks are reproducible)."""
    delays = []
    state = (seed * 2654435761 + 12345) & 0xFFFFFFFF
    for a in range(max(attempts - 1, 0)):
        state = (state * 1103515245 + 12345) & 0x7FFFFFFF
        frac = 0.5 + (state / 0x7FFFFFFF) * 0.5          # [0.5, 1.0)
        delays.append(round(min(cap, base * (2 ** a)) * frac, 2))
    return delays


#: the probe child: dumps its own tracebacks and exits shortly BEFORE the
#: parent's kill lands, so a hung platform bind still leaves evidence on
#: stderr.  `_LGBM_TPU_PROBE_HANG` carries the injected hang (computed by
#: the parent from the fault spec; a real dead tunnel hangs inside the
#: jax import/device init itself and is caught the same way).
_PROBE_CHILD = r"""
import faulthandler, os, sys, time
faulthandler.dump_traceback_later(%(dump_after)f, exit=True)
hang = float(os.environ.get("_LGBM_TPU_PROBE_HANG", "0"))
if hang > 0:
    time.sleep(hang)
import jax
print("platform=%%s devices=%%d" %% (jax.default_backend(),
                                     len(jax.devices())), flush=True)
"""


def probe_platform(platform: Optional[str] = None, deadline: float = 20.0,
                   n_devices: Optional[int] = None) -> Dict[str, Any]:
    """One short-deadline subprocess probe of backend init.

    Returns a machine-readable record: ``{"ok": bool, "platform":
    requested, "backend": reported backend or None, "rc", "dur_s",
    "reason", "tail"}``.  Never hangs: the child self-dumps + self-exits
    just before `deadline`, and the parent kills it at `deadline` if even
    that failed."""
    env = dict(os.environ)
    req = platform if platform is not None else env.get("JAX_PLATFORMS") or None
    if fault_active("bogus_platform") and (req is None or req != "cpu"):
        req = "bogus"
    if req is not None:
        env["JAX_PLATFORMS"] = req
    hang = maybe_probe_hang_seconds(req)
    if hang > 0:
        env["_LGBM_TPU_PROBE_HANG"] = str(hang)
    if n_devices and (req is None or req == "cpu"):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=%d"
                            % n_devices).strip()
    code = _PROBE_CHILD % {"dump_after": max(deadline - 2.0, 1.0)}
    t0 = time.monotonic()
    rec: Dict[str, Any] = {"platform": req or "<default>", "ok": False,
                           "backend": None, "rc": None, "reason": None,
                           "t_start": wallclock()}
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           timeout=deadline, capture_output=True, text=True)
        rec["rc"] = r.returncode
        out = (r.stdout or "").strip().splitlines()
        tail = (r.stderr or "")[-2000:]
        if r.returncode == 0 and out and out[-1].startswith("platform="):
            rec["ok"] = True
            rec["backend"] = out[-1].split("platform=", 1)[1].split()[0]
        elif "Timeout" in tail or "dump_traceback_later" in tail \
                or r.returncode != 0 and "Thread 0x" in tail:
            rec["reason"] = "hang (child self-dumped at deadline)"
            rec["tail"] = tail
        else:
            rec["reason"] = "init failed (rc=%d)" % r.returncode
            rec["tail"] = tail
    except subprocess.TimeoutExpired as e:
        rec["rc"] = -9
        rec["reason"] = "hang (parent killed the probe at %.0fs)" % deadline
        rec["tail"] = ((e.stderr or b"").decode("utf-8", "replace")
                       if isinstance(e.stderr, bytes) else (e.stderr or ""))[-2000:]
    rec["dur_s"] = round(time.monotonic() - t0, 2)
    return rec


def resolve_backend(requested: Optional[str] = None, deadline: float = 20.0,
                    attempts: int = 2, n_devices: Optional[int] = None,
                    ) -> Tuple[str, Optional[Dict[str, Any]], List[Dict]]:
    """Degradation chain: probe `requested` (default: the environment's
    JAX_PLATFORMS) up to `attempts` times with jittered backoff, then
    degrade to cpu.  Returns ``(backend, degradation_event_or_None,
    probe_trail)``; `degradation_event` is the machine-readable record
    the artifact JSON carries:

        {"event": "platform_degradation", "from": ..., "to": "cpu",
         "reason": ..., "attempts": N, "probes": [...], "wallclock": ...}
    """
    req = requested if requested is not None \
        else os.environ.get("JAX_PLATFORMS") or None
    if fault_active("bogus_platform") and (req is None or req != "cpu"):
        req = "bogus"
    trail: List[Dict[str, Any]] = []
    if req is None or req == "cpu":
        rec = probe_platform("cpu", deadline=deadline, n_devices=n_devices)
        trail.append(rec)
        return "cpu", None, trail
    delays = backoff_delays(attempts)
    for a in range(attempts):
        rec = probe_platform(req, deadline=deadline)
        trail.append(rec)
        if rec["ok"]:
            return req, None, trail
        if a < len(delays):
            time.sleep(delays[a])
    event = {
        "event": "platform_degradation",
        "from": req, "to": "cpu",
        "reason": trail[-1].get("reason") or "probe failed",
        "attempts": attempts,
        "probes": [{k: v for k, v in t.items() if k != "tail"}
                   for t in trail],
        "wallclock": wallclock(),
    }
    cpu_rec = probe_platform("cpu", deadline=max(deadline, 30.0),
                             n_devices=n_devices)
    trail.append(cpu_rec)
    return "cpu", event, trail


# ---------------------------------------------------------------------------
# atomic snapshot writes + checksum + retention
# ---------------------------------------------------------------------------

def atomic_write(path: str, text: str) -> None:
    """tmp + flush + fsync + rename in the destination directory: a
    crash at any point leaves either the old file or the new one, never
    a torn half-write, and never a stray ``*.snapshot_iter_*`` tmp."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".%s.tmp" % os.path.basename(path),
                               dir=d)
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def read_stage_report(path: str) -> Optional[Dict[str, Any]]:
    """Tolerant stage-trail reader for scrapers and artifact wrappers:
    returns the report dict, or None for a missing, unreadable, torn or
    non-JSON file.  Writers go through `atomic_write`, so a torn file
    means a non-cooperating writer (or a dying filesystem) — the reader
    must degrade to "no trail", never crash the post-mortem."""
    try:
        with open(path) as fh:
            rep = json.load(fh)
    except (OSError, ValueError):
        return None
    return rep if isinstance(rep, dict) else None


_STATE_PREFIX = "!snapshot_state="
_CHECKSUM_PREFIX = "!snapshot_checksum=sha256:"


def _with_footer(model_text: str, state: Dict[str, Any]) -> str:
    """Model text + state footer + checksum line.  The footer lives past
    'end of trees', where the model parser only greps for the parameters
    block — a snapshot file IS a loadable model file."""
    blob = base64.b64encode(
        zlib.compress(json.dumps(state).encode())).decode()
    body = model_text
    if not body.endswith("\n"):
        body += "\n"
    body += _STATE_PREFIX + blob + "\n"
    digest = hashlib.sha256(body.encode()).hexdigest()
    return body + _CHECKSUM_PREFIX + digest + "\n"


def validate_snapshot(path: str) -> Tuple[bool, str]:
    """(ok, reason).  A snapshot is valid iff it ends with a checksum
    line whose sha256 matches everything before it and its state footer
    decodes — truncated, torn and bit-flipped files all fail."""
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError as e:
        return False, "unreadable: %s" % e
    text = raw.decode("utf-8", "replace")
    lines = text.rstrip("\n").split("\n")
    if not lines or not lines[-1].startswith(_CHECKSUM_PREFIX):
        return False, "missing checksum footer (truncated?)"
    digest = lines[-1][len(_CHECKSUM_PREFIX):].strip()
    body = text[: text.rfind(_CHECKSUM_PREFIX)]
    if hashlib.sha256(body.encode()).hexdigest() != digest:
        return False, "checksum mismatch (torn or corrupted write)"
    if load_snapshot_state(path, _prevalidated_text=text) is None:
        return False, "state footer missing or undecodable"
    return True, "ok"


def load_snapshot_state(path: str, _prevalidated_text: Optional[str] = None
                        ) -> Optional[Dict[str, Any]]:
    """The state dict from a snapshot's footer, or None."""
    try:
        if _prevalidated_text is None:
            with open(path) as fh:
                _prevalidated_text = fh.read()
        for line in reversed(_prevalidated_text.rstrip("\n").split("\n")):
            if line.startswith(_STATE_PREFIX):
                blob = line[len(_STATE_PREFIX):].strip()
                return json.loads(zlib.decompress(
                    base64.b64decode(blob)).decode())
    except (OSError, ValueError, zlib.error, json.JSONDecodeError):
        return None
    return None


def snapshot_model_text(path: str) -> Optional[str]:
    """The model-text portion of a snapshot file (everything before the
    state footer) — what `save_model_to_string()` produced at capture
    time, byte-for-byte.  The continuous trainer republishes from this
    after a death between snapshot and publish, so the republished
    generation is byte-identical to what the dead process would have
    published.  None when the file has no footer (not a snapshot)."""
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return None
    cut = text.find(_STATE_PREFIX)
    if cut < 0:
        return None
    return text[:cut]


def snapshot_paths(output_model: str) -> List[Tuple[int, str]]:
    """Existing ``<output_model>.snapshot_iter_<N>`` files, newest first."""
    d = os.path.dirname(os.path.abspath(output_model)) or "."
    base = os.path.basename(output_model) + ".snapshot_iter_"
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if name.startswith(base):
            tail = name[len(base):]
            if tail.isdigit():
                out.append((int(tail), os.path.join(d, name)))
    out.sort(reverse=True)
    return out


def find_resume_snapshot(output_model: str, log=None
                         ) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    """Newest VALID snapshot for `output_model`, scanning past corrupt /
    truncated ones with a logged warning for each."""
    def warn(msg, *args):
        if log is not None:
            log.warning(msg, *args)
        else:
            sys.stderr.write("resilience: " + (msg % args) + "\n")

    for it, path in snapshot_paths(output_model):
        ok, reason = validate_snapshot(path)
        if ok:
            return path, load_snapshot_state(path)
        warn("snapshot %s is invalid (%s); falling back to the previous "
             "one", path, reason)
    return None, None


# ---------------------------------------------------------------------------
# training-state capture / restore (byte-identical resume)
# ---------------------------------------------------------------------------

def _b64_np(arr) -> str:
    import numpy as np
    a = np.ascontiguousarray(arr)
    return base64.b64encode(zlib.compress(a.tobytes())).decode()


def _np_b64(blob: str, dtype, shape):
    import numpy as np
    raw = zlib.decompress(base64.b64decode(blob))
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def _rng_state_to_json(rng) -> Dict[str, Any]:
    """numpy Generator (Philox) state -> JSON-able dict."""
    import numpy as np

    def conv(v):
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, np.ndarray):
            return {"__nd__": v.dtype.str, "data": v.tolist()}
        if isinstance(v, (np.integer,)):
            return int(v)
        return v

    return conv(rng._rng.bit_generator.state)


def _rng_state_from_json(rng, state: Dict[str, Any]) -> None:
    import numpy as np

    def conv(v):
        if isinstance(v, dict):
            if "__nd__" in v:
                return np.asarray(v["data"], dtype=np.dtype(v["__nd__"]))
            return {k: conv(x) for k, x in v.items()}
        return v

    rng._rng.bit_generator.state = conv(state)


def _params_fingerprint(raw_params: Dict[str, Any]) -> str:
    items = sorted((str(k), str(v)) for k, v in raw_params.items())
    return hashlib.sha256(json.dumps(items).encode()).hexdigest()[:16]


def capture_training_state(booster) -> Dict[str, Any]:
    """Everything a resumed run needs to continue BYTE-IDENTICALLY to an
    uninterrupted one, beyond the trees themselves: the padded raw score
    planes, the fast path's payload row order (histogram accumulation is
    f32 and therefore order-sensitive), the bagging mask + both host RNG
    streams, and the boosting variant's bookkeeping (DART drop RNG /
    tree weights).  Mesh runs skip the row order (rows are reordered per
    shard) — resume still works, but exactness is only guaranteed for
    serial training; the state records which case it captured."""
    import numpy as np
    from . import syncs
    eng = booster._engine
    if eng is None:
        raise RuntimeError("capture_training_state needs a training Booster")
    # snapshots observe the model AND the scores: drain the dispatch
    # pipeline first (flush barrier contract, ISSUE 5) and settle any
    # open boosting window at the reported iteration (ISSUE 13)
    eng.flush(sync_scores=True)
    if eng._fast_active:
        score = eng._fast.raw_scores()                      # [K, n_pad] f32
        perm = (eng._fast.host_idx().astype(np.int32)
                if eng.mesh is None else None)
    else:
        score = np.asarray(syncs.device_get(eng.score, label="snapshot"),
                           np.float32)
        perm = None
    state: Dict[str, Any] = {
        "version": 1,
        "total_iter": int(eng.model.current_iteration),
        "boosting": type(eng).__name__,
        "K": int(eng.num_tree_per_iteration),
        "n_pad": int(eng.train_set.num_data_padded),
        "num_data": int(eng.train_set.num_data),
        "score": _b64_np(score),
        "perm": _b64_np(perm) if perm is not None else None,
        "perm_len": int(perm.size) if perm is not None else 0,
        "bag_mask": _b64_np(np.packbits(eng.bag_mask_host > 0)),
        "bagging_rng": _rng_state_to_json(eng.bagging_rng),
        "feature_rng": _rng_state_to_json(eng.feature_rng),
        "shrinkage_rate": float(eng.shrinkage_rate),
        "boosted_from_average": bool(eng._boosted_from_average),
        "init_score_value": float(eng.init_score_value),
        "params_fingerprint": _params_fingerprint(
            getattr(eng.config, "raw_params", {})),
    }
    if hasattr(eng, "random_for_drop"):                     # DART
        state["dart"] = {
            "drop_rng": _rng_state_to_json(eng.random_for_drop),
            "tree_weight": [float(w) for w in eng.tree_weight],
            "sum_weight": float(eng.sum_weight),
        }
    return state


def restore_training_state(booster, state: Dict[str, Any], log=None) -> None:
    """Surgery on a freshly constructed Booster (init_model = the
    snapshot's trees) that makes its next iteration arithmetically
    identical to the uninterrupted run's:

    * the padded raw scores are installed verbatim (the init replay's
      f32 re-quantization of f64 leaf values is overwritten);
    * the iteration counter moves to the engine-global clock
      (``iter = total, num_init_iteration = 0``) so bagging schedules,
      GOSS warmup/fold-in and DART drop candidates see the same history
      an uninterrupted run would;
    * both host RNG streams (bagging / feature sampling) and the DART
      drop RNG + tree-weight ledger resume mid-stream;
    * on the serial fast path, the payload is rebuilt and then permuted
      into the EXACT row order the snapshot captured — f32 histogram
      accumulation is order-sensitive, so row order is training state.
    """
    import jax.numpy as jnp
    import numpy as np

    def warn(msg, *args):
        if log is not None:
            log.warning(msg, *args)
        else:
            sys.stderr.write("resilience: " + (msg % args) + "\n")

    eng = booster._engine
    if eng is None:
        raise RuntimeError("restore_training_state needs a training Booster")
    K, n_pad = int(state["K"]), int(state["n_pad"])
    if (K != eng.num_tree_per_iteration
            or n_pad != eng.train_set.num_data_padded
            or int(state["num_data"]) != eng.train_set.num_data):
        warn("snapshot shape (K=%d, n_pad=%d) does not match this dataset "
             "(K=%d, n_pad=%d); resuming with plain continued-training "
             "semantics instead", K, n_pad, eng.num_tree_per_iteration,
             eng.train_set.num_data_padded)
        return
    fp = _params_fingerprint(getattr(eng.config, "raw_params", {}))
    if state.get("params_fingerprint") not in (None, fp):
        warn("training parameters differ from the snapshot's; the resumed "
             "model may not be byte-identical to an uninterrupted run")

    eng.score = jnp.asarray(_np_b64(state["score"], np.float32, (K, n_pad)))
    eng.iter = int(state["total_iter"])
    eng.num_init_iteration = 0
    eng.shrinkage_rate = float(state["shrinkage_rate"])
    eng._boosted_from_average = bool(state["boosted_from_average"])
    eng.init_score_value = float(state["init_score_value"])
    bag_bits = _np_b64(state["bag_mask"], np.uint8, (-1,))
    mask = np.unpackbits(bag_bits)[:n_pad].astype(np.float32)
    eng.bag_mask_host = mask
    eng._bag_cmask = jnp.asarray(mask)
    _rng_state_from_json(eng.bagging_rng, state["bagging_rng"])
    _rng_state_from_json(eng.feature_rng, state["feature_rng"])
    if "dart" in state and hasattr(eng, "random_for_drop"):
        _rng_state_from_json(eng.random_for_drop, state["dart"]["drop_rng"])
        eng.tree_weight = [float(w) for w in state["dart"]["tree_weight"]]
        eng.sum_weight = float(state["dart"]["sum_weight"])

    if state.get("perm") and eng.mesh is None and eng._fast_eligible():
        fs = eng._fast_enter()          # identity-ordered fresh payload
        perm = _np_b64(state["perm"], np.int32, (int(state["perm_len"]),))
        if perm.size == fs.n_rows:
            # row j of the uninterrupted payload held original row
            # perm[j]; guard rows (idx == n_pad) all share one dead-slot
            # content, so any guard position sources them
            src = np.where(perm < n_pad, perm, n_pad).astype(np.int32)
            fs.payload = jnp.take(fs.payload, jnp.asarray(src), axis=0)
            fs._bag_dirty = True
        else:
            warn("snapshot payload order length %d does not match the "
                 "rebuilt payload (%d rows); resuming in identity order "
                 "(model may differ in low-order bits)",
                 perm.size, fs.n_rows)


def make_resume_callback(state: Dict[str, Any], log=None):
    """A before_iteration callback that performs the restore exactly once,
    before the first resumed iteration runs (the train() driver owns
    Booster construction, so this is the earliest seam)."""
    done = {"flag": False}

    def _callback(env) -> None:
        if done["flag"]:
            return
        done["flag"] = True
        restore_training_state(env.model, state, log=log)

    _callback.before_iteration = True
    _callback.order = 0
    return _callback


def write_snapshot(booster, output_model: str, total_iter: Optional[int] = None,
                   retention: int = -1, log=None,
                   extra_state: Optional[Dict[str, Any]] = None,
                   retention_grace_s: float = 0.0) -> Optional[str]:
    """Atomic snapshot ``<output_model>.snapshot_iter_<N>`` carrying the
    model plus the resume state footer, with keep-last-`retention`
    cleanup (``<= 0`` keeps everything).  Refuses to snapshot non-finite
    scores (a poisoned snapshot would just re-poison the resume).

    `extra_state` is merged under the footer's ``"service"`` key — the
    continuous trainer records its schedule clock there; resume ignores
    unknown keys, so plain `task=train` snapshots are unaffected.

    `retention_grace_s > 0` hardens keep-last-K against concurrent
    readers: a snapshot beyond the K newest is only unlinked once it is
    also OLDER than the grace window, so a reader that just resolved a
    path (a resume scan racing the trainer, a debugging copy) cannot
    have the file deleted out from under it mid-read.  The default 0
    keeps the historical behavior for batch training, where pruning only
    runs in the single writer process."""
    import numpy as np
    state = capture_training_state(booster)
    if extra_state:
        state["service"] = dict(extra_state)
    if total_iter is None:
        total_iter = state["total_iter"]
    score = _np_b64(state["score"], np.float32,
                    (state["K"], state["n_pad"]))
    if not np.isfinite(score).all():
        if log is not None:
            log.warning("scores are non-finite at iteration %d; snapshot "
                        "NOT written", total_iter)
        return None
    path = "%s.snapshot_iter_%d" % (output_model, total_iter)
    atomic_write(path, _with_footer(
        booster._model.save_model_to_string(), state))
    maybe_corrupt_snapshot(path, total_iter)
    if retention > 0:
        cutoff = time.time() - max(retention_grace_s, 0.0)
        for it, old in snapshot_paths(output_model)[retention:]:
            with contextlib.suppress(OSError):
                if retention_grace_s <= 0 or os.path.getmtime(old) < cutoff:
                    os.unlink(old)
    return path


# ---------------------------------------------------------------------------
# preemption guard (SIGTERM/SIGINT -> final snapshot -> exit)
# ---------------------------------------------------------------------------

class TrainingPreempted(Exception):
    """Raised at the iteration boundary after a preemption signal; the
    final snapshot has already been written when this propagates."""

    def __init__(self, signum: int, iteration: int,
                 snapshot: Optional[str]):
        super().__init__("training preempted by signal %d at iteration %d"
                         % (signum, iteration))
        self.signum = signum
        self.iteration = iteration
        self.snapshot = snapshot


class PreemptionGuard:
    """SIGTERM/SIGINT -> write-final-snapshot-then-exit, at the next
    iteration boundary (Python delivers signals between bytecodes, but
    mid-iteration state — a half-appended multiclass iteration, an
    in-flight device dispatch — is not snapshotable; one iteration is
    the guaranteed preemption latency bound).

    Use as a context manager around the training loop; `callback` goes
    LAST in the after-iteration callback list."""

    def __init__(self, output_model: str, retention: int = -1, log=None):
        self.output_model = output_model
        self.retention = retention
        self.log = log
        self.signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}

        def _callback(env) -> None:
            if self.signum is None:
                return
            total = int(env.model.current_iteration())
            snap = write_snapshot(env.model, self.output_model,
                                  total_iter=total,
                                  retention=self.retention, log=self.log)
            raise TrainingPreempted(self.signum, total, snap)

        _callback.order = 100
        self.callback = _callback

    def _handler(self, signum, frame):
        self.signum = signum
        sys.stderr.write("[%s] preemption signal %d received; writing a "
                         "final snapshot at the next iteration boundary\n"
                         % (wallclock(), signum))
        sys.stderr.flush()

    def __enter__(self) -> "PreemptionGuard":
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev[sig] = signal.signal(sig, self._handler)
            except ValueError:
                pass   # not the main thread: guard inert, training unchanged
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._prev.items():
            with contextlib.suppress(ValueError):
                signal.signal(sig, prev)
        return None


# ---------------------------------------------------------------------------
# non-finite sentinel
# ---------------------------------------------------------------------------

class NonFiniteDetected(ArithmeticError):
    """A freshly grown tree carried NaN/inf outputs (the device-side
    symptom of a non-finite grad/hess/score burst)."""

    def __init__(self, iteration: int, tree_index: int, field: str):
        super().__init__(
            "non-finite %s detected in the tree grown at iteration %d "
            "(tree %d)" % (field, iteration, tree_index))
        self.iteration = iteration
        self.tree_index = tree_index
        self.field = field


def sentinel_check(engine, host: Dict) -> None:
    """Screen the tree outputs fetched from device this iteration (free:
    `_finish_tree` already pulled them to host).  Policy 'off' skips the
    scan entirely; 'abort'/'rollback' raise `NonFiniteDetected` for
    `SentinelGuard` to arbitrate."""
    import numpy as np
    policy = getattr(engine, "_sentinel_policy", "off")
    if policy == "off":
        return
    maybe_inject_nan(engine, host)
    nl = max(int(host["num_leaves"]), 1)
    if not np.isfinite(host["leaf_value"][:nl]).all():
        raise NonFiniteDetected(int(engine.iter),
                                len(engine.model.trees), "leaf values")
    if nl > 1 and not np.isfinite(host["internal_value"][:nl - 1]).all():
        raise NonFiniteDetected(int(engine.iter),
                                len(engine.model.trees), "internal values")


class SentinelGuard:
    """Pre-iteration state for the abort-vs-rollback policy.

    'abort' re-raises as a hard error naming the iteration; 'rollback'
    restores the pre-iteration scores (captured to host when the policy
    is armed — one D2H per iteration, the documented cost of the
    feature), drops the iteration's trees, and STOPS training cleanly
    (the gradient source is producing non-finites; continuing would
    poison every later tree)."""

    def __init__(self, engine):
        from . import syncs
        self.engine = engine
        self.policy = getattr(engine, "_sentinel_policy", "off")
        self.pre_trees = len(engine.model.trees)
        self.pre_iter = int(engine.iter)
        self.score = None
        if self.policy == "rollback":
            if engine._fast_active:
                self.score = engine._fast.raw_scores()
            else:
                self.score = syncs.device_get(engine.score,
                                              label="sentinel")

    def handle(self, err: NonFiniteDetected, log) -> bool:
        """Returns True (= training finished) after a rollback; raises
        for the abort policy.  Mirrors the Booster.update contract."""
        if self.policy != "rollback" or self.score is None:
            raise type(err)(err.iteration, err.tree_index, err.field)
        import jax.numpy as jnp
        eng = self.engine
        del eng.model.trees[self.pre_trees:]
        eng.iter = self.pre_iter
        # discard the poisoned payload outright (a sync-back would copy
        # the NaNs); the next fast entry rebuilds from the restored score
        eng._fast_active = False
        eng.score = jnp.asarray(self.score)
        log.warning(
            "%s; policy=rollback: iteration %d discarded, scores restored, "
            "training stopped", err, err.iteration)
        return True
