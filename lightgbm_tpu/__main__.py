"""`python -m lightgbm_tpu` — the CLI entry (reference src/main.cpp)."""
from .application import main

main()
