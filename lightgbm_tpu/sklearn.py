"""scikit-learn estimator API.

Role parity with the reference python-package/lightgbm/sklearn.py
(LGBMModel:128, LGBMRegressor:650, LGBMClassifier:676, LGBMRanker:800,
objective/eval closures via _ObjectiveFunctionWrapper/_EvalFunctionWrapper
:17-127).  Works with or without scikit-learn installed: when available the
estimators inherit BaseEstimator so grid-search/pipeline/clone work.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train
from .utils.log import LightGBMError

try:  # pragma: no cover - environment-dependent
    from sklearn.base import BaseEstimator as _SKBase
except Exception:  # sklearn absent
    _SKBase = object


class _Base(_SKBase):
    """get/set_params that also surface **kwargs pass-through params, so
    clone/GridSearchCV see them (reference sklearn.py get_params override)."""

    def _named_params(self) -> List[str]:
        import inspect
        return [k for k in inspect.signature(self.__init__).parameters
                if k != "kwargs"]

    def get_params(self, deep: bool = True) -> Dict:
        out = {k: getattr(self, k) for k in self._named_params()}
        out.update(getattr(self, "_other_params", {}))
        return out

    def set_params(self, **params) -> "_Base":
        named = set(self._named_params())
        for k, v in params.items():
            setattr(self, k, v)
            if k not in named:
                self._other_params[k] = v
        return self


class _ObjectiveFunctionWrapper:
    """Wrap a sklearn-style objective fn(y_true, y_pred[, group]) -> (grad,
    hess) into the engine's fobj(preds, dataset) (sklearn.py:17-84)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_group())
        else:
            raise TypeError("Self-defined objective should have 2 or 3 arguments")
        return np.asarray(grad), np.asarray(hess)


class _EvalFunctionWrapper:
    """Wrap fn(y_true, y_pred[, weight[, group]]) -> (name, value,
    is_higher_better) into feval(preds, dataset) (sklearn.py:86-127)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError("Self-defined eval function should have 2, 3 or 4 arguments")


def _compute_class_sample_weight(y, class_weight, sample_weight):
    """'balanced' or {label: weight} per-sample weights multiplied into any
    explicit sample_weight (reference _LGBMComputeSampleWeight usage)."""
    if class_weight is None:
        return sample_weight
    classes, counts = np.unique(y, return_counts=True)
    if class_weight == "balanced":
        w_map = {c: len(y) / (len(classes) * cnt)
                 for c, cnt in zip(classes, counts)}
    elif isinstance(class_weight, dict):
        w_map = class_weight
    else:
        raise LightGBMError("class_weight must be 'balanced' or a dict")
    w = np.asarray([w_map.get(v, 1.0) for v in y], dtype=np.float64)
    if sample_weight is not None:
        w = w * np.asarray(sample_weight, dtype=np.float64)
    return w


class LGBMModel(_Base):
    """Base estimator (sklearn.py LGBMModel:128-649)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Any] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True, importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params: Dict = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._best_score: Dict = {}
        self._n_features = 0
        self._objective = objective
        self._n_classes = 1

    # -- param plumbing ------------------------------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _engine_params(self) -> Dict:
        params = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbose": -1 if self.silent else 1,
        }
        if self.random_state is not None:
            params["seed"] = int(self.random_state)
        obj = self._objective
        params["objective"] = obj if isinstance(obj, str) and obj else \
            ("none" if callable(obj) else self._default_objective())
        params.update(self._other_params)
        return params

    # -- training ------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None, early_stopping_rounds=None,
            verbose: bool = False, feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMModel":
        # re-read every fit so set_params(objective=...) takes effect
        self._objective = self.objective
        # the CONCRETE objective (sklearn objective_ fitted attribute):
        # the callable itself, or the resolved string incl. the default
        self._fit_objective = (
            self._objective if callable(self._objective)
            else (self._objective if isinstance(self._objective, str)
                  and self._objective else self._default_objective()))
        fobj = _ObjectiveFunctionWrapper(self._objective) if callable(self._objective) else None
        feval = _EvalFunctionWrapper(eval_metric) if callable(eval_metric) else None
        params = self._engine_params()
        if isinstance(eval_metric, str):
            params["metric"] = eval_metric
        elif isinstance(eval_metric, (list, tuple)):
            params["metric"] = ",".join(eval_metric)

        X_orig = X
        X = np.asarray(X, dtype=np.float64) if not hasattr(X, "values") else X
        self._n_features = np.asarray(X).shape[1]
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if (vx is X or vx is X_orig) and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                valid_sets.append(train_set.create_valid(
                    vx, label=self._prepare_y(vy), weight=vw, group=vg))

        self._evals_result = {}
        cbs = list(callbacks) if callbacks else []
        from .callback import record_evaluation
        cbs.append(record_evaluation(self._evals_result))
        self._Booster = train(params, train_set,
                              num_boost_round=self.n_estimators,
                              valid_sets=valid_sets or None,
                              valid_names=eval_names,
                              fobj=fobj, feval=feval,
                              early_stopping_rounds=early_stopping_rounds,
                              callbacks=cbs,
                              verbose_eval=verbose)
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def _prepare_y(self, y) -> np.ndarray:
        return np.asarray(y, dtype=np.float64).reshape(-1)

    # -- prediction ----------------------------------------------------------
    def predict(self, X, raw_score: bool = False, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before predict")
        if num_iteration is None or num_iteration < 0:
            num_iteration = self._best_iteration if self._best_iteration > 0 else -1
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib, **kwargs)

    # -- sklearn attributes --------------------------------------------------
    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster

    @property
    def objective_(self):
        """The concrete objective used while fitting (sklearn.py
        objective_ fitted attribute)."""
        if self._Booster is None:
            raise LightGBMError("No objective found, call fit first")
        return self._fit_objective

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(importance_type=self.importance_type)


class LGBMRegressor(LGBMModel):
    def _default_objective(self) -> str:
        return "regression"

    def score(self, X, y, sample_weight=None):
        """R^2 (the sklearn RegressorMixin contract, which GridSearchCV
        relies on when no scoring is given)."""
        y = np.asarray(y, dtype=np.float64)
        pred = np.asarray(self.predict(X), dtype=np.float64)
        w = None if sample_weight is None else np.asarray(sample_weight)
        avg = np.average(y, weights=w)
        ss_res = np.average((y - pred) ** 2, weights=w)
        ss_tot = np.average((y - avg) ** 2, weights=w)
        if ss_tot > 0:
            return 1.0 - ss_res / ss_tot
        # constant target: sklearn's r2_score convention
        return 1.0 if ss_res == 0 else 0.0


class LGBMClassifier(LGBMModel):
    def _default_objective(self) -> str:
        return "multiclass" if self._n_classes > 2 else "binary"

    def score(self, X, y, sample_weight=None):
        """Accuracy (the sklearn ClassifierMixin contract)."""
        pred = np.asarray(self.predict(X))
        hits = (pred == np.asarray(y)).astype(np.float64)
        return float(np.average(hits, weights=sample_weight))

    def fit(self, X, y, sample_weight=None, **kwargs):
        y = np.asarray(y).reshape(-1)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._class_map = {c: i for i, c in enumerate(self._classes)}
        y_enc = np.asarray([self._class_map[v] for v in y], dtype=np.float64)
        # num_class must track THIS fit, not a previous one
        if self._n_classes > 2:
            self._other_params["num_class"] = self._n_classes
        else:
            self._other_params.pop("num_class", None)
        sample_weight = _compute_class_sample_weight(y, self.class_weight,
                                                    sample_weight)
        super().fit(X, y_enc, sample_weight=sample_weight, **kwargs)
        return self

    def _prepare_y(self, y) -> np.ndarray:
        y = np.asarray(y).reshape(-1)
        unseen = set(np.unique(y)) - set(self._class_map)
        if unseen:
            raise LightGBMError(
                "Eval set contains labels unseen during fit: %s" % sorted(unseen))
        return np.asarray([self._class_map[v] for v in y], dtype=np.float64)

    @property
    def classes_(self) -> np.ndarray:
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(X, raw_score=raw_score,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        return self._classes[np.argmax(result, axis=1)]

    def predict_proba(self, X, raw_score: bool = False, num_iteration: int = -1,
                      pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        result = super().predict(X, raw_score=raw_score,
                                 num_iteration=num_iteration,
                                 pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes > 2:
            return result  # already [n, K] probabilities
        return np.vstack([1.0 - result, result]).T


class LGBMRanker(LGBMModel):
    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise LightGBMError("Should set group for ranking task")
        kwargs.setdefault("eval_group", None)
        if kwargs.get("eval_set") is not None and kwargs.get("eval_group") is None:
            raise LightGBMError("Eval_group cannot be None when eval_set is not None")
        super().fit(X, y, group=group, **kwargs)
        return self
