// Training side of the C ABI: LGBM_Dataset* / LGBM_BoosterCreate /
// LGBM_BoosterUpdateOneIter[Custom] parity with the reference c_api
// (include/LightGBM/c_api.h:48-460, src/c_api.cpp Booster/Dataset
// sections), driving THIS framework's real training engine in-process by
// embedding CPython.
//
// Design: the reference's C training surface is a marshalling layer over
// its C++ Booster; ours is a marshalling layer over the JAX engine (the
// compute path is XLA either way — the C caller gets the same TPU
// kernels as a Python caller).  A trained booster carries a native
// Model* cache (c_api.cc) re-parsed from its model text after every
// update, so every existing prediction/save entry point serves trained
// and loaded boosters with the exact same hardware-validated code.
//
// The embedded interpreter initializes lazily on the first training
// call; prediction-only users never start Python.  All entry points are
// GIL-correct (PyGILState_Ensure/Release) and may be called from any
// thread.
#include "lightgbm_tpu_c_api.h"
#include "c_internal.h"

#include <Python.h>
#include <dlfcn.h>

#include <atomic>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <string>

namespace {

using lgbm_tpu_internal::kTrainBoosterMagic;
using lgbm_tpu_internal::kTrainDatasetMagic;
using lgbm_tpu_internal::HandleMagic;
using lgbm_tpu_internal::SetLastError;

struct TrainDataset {
  const uint32_t magic = kTrainDatasetMagic;
  PyObject* ds = nullptr;  // lightgbm_tpu.Dataset
  // GetField contract: the returned pointer stays valid until the next
  // GetField on this handle (or DatasetFree) — the bytes live here
  std::string field_buf;
};

struct TrainBooster {
  const uint32_t magic = kTrainBoosterMagic;
  PyObject* bst = nullptr;      // lightgbm_tpu.Booster
  void* native = nullptr;       // cached LGBM_BoosterLoadModelFromString
  std::atomic<bool> dirty{true};  // model changed since last native sync
  std::mutex sync_mu;           // serializes the parse-and-swap itself
  // Reader/writer guard on the cached Model*: every predict/save holds it
  // SHARED for the whole time it dereferences the pointer (taken inside
  // TrainBoosterNative, released via the booster_native_release hook), and
  // the resync takes it EXCLUSIVE only around the free/swap — so an
  // UpdateOneIter racing an in-flight predict can no longer free the
  // model under the reader, making the header's "any thread" contract
  // actually true (the reference c_api guards Booster the same way).
  std::shared_mutex model_mu;
};

// Helper functions executed inside the embedded interpreter.  Keeping the
// marshalling in Python keeps the C side to plain PyObject_CallMethod
// calls; everything here routes straight into the public package API.
const char* kHelperSource = R"PY(
import numpy as np
import lightgbm_tpu as lgb


def _params(s):
    out = {}
    for tok in (s or '').replace('\t', ' ').replace(',', ' ').split():
        if '=' in tok:
            k, v = tok.split('=', 1)
            out[k] = v
    return out


def dataset_from_file(fname, params, ref):
    return lgb.Dataset(fname, reference=ref, params=_params(params),
                       free_raw_data=False)


def dataset_from_mat(mv, dtype_code, nrow, ncol, is_row_major, params, ref):
    dt = np.float32 if dtype_code == 0 else np.float64
    a = np.frombuffer(mv, dtype=dt)
    a = a.reshape(nrow, ncol) if is_row_major else a.reshape(ncol, nrow).T
    return lgb.Dataset(np.array(a, copy=True), reference=ref,
                       params=_params(params), free_raw_data=False)


def dataset_set_field(ds, name, mv, dtype_code):
    dt = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}[dtype_code]
    ds.set_field(name, np.frombuffer(mv, dtype=dt).copy())


def dataset_get_field(ds, name):
    ds.construct()
    v = ds.get_field(name)
    if v is None:
        raise KeyError('field %r is not set on this dataset' % name)
    v = np.asarray(v)
    if name in ('group', 'query'):
        # reference GetField('group') returns CUMULATIVE query
        # boundaries (num_queries + 1 int32), not the sizes SetField took
        v = np.concatenate([[0], np.cumsum(v.astype(np.int64))]) \
            .astype(np.int32)
        code = 2
    elif name == 'init_score':
        v = np.ascontiguousarray(v, dtype=np.float64).reshape(-1)
        code = 1
    else:
        v = np.ascontiguousarray(v, dtype=np.float32).reshape(-1)
        code = 0
    return (v.tobytes(), code, int(v.size))


def dataset_feature_num_bin(ds, i):
    ds.construct()
    mappers = ds.binned.bin_mappers
    if i < 0 or i >= len(mappers):
        raise IndexError('feature index %d out of range (%d features)'
                         % (i, len(mappers)))
    return int(mappers[i].num_bin)


def dataset_from_mats(mvs, dtype_code, nrows, ncol, is_row_major, params,
                      ref):
    dt = np.float32 if dtype_code == 0 else np.float64
    parts = []
    for mv, nr in zip(mvs, nrows):
        a = np.frombuffer(mv, dtype=dt)
        a = a.reshape(nr, ncol) if is_row_major else a.reshape(ncol, nr).T
        parts.append(np.array(a, copy=True))
    X = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
    return lgb.Dataset(X, reference=ref, params=_params(params),
                       free_raw_data=False)


def _as_np(mv, dtype_code, count):
    # copy: the C caller's buffer lifetime ends when the entry point
    # returns, but the chunk lives in the stream builder until finalize
    dt = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}[dtype_code]
    return np.frombuffer(mv, dtype=dt, count=count).copy()


def _stream_builder(params, num_features=None, reference=None,
                    num_total_rows=None):
    from lightgbm_tpu.io.stream import StreamingDatasetBuilder
    return StreamingDatasetBuilder(params=params, num_features=num_features,
                                   reference=reference,
                                   num_total_rows=num_total_rows)


def dataset_from_csr(ipmv, ipcode, idxmv, dmv, dcode, nindptr, nelem,
                     num_col, params, ref):
    p = _params(params)
    indptr = _as_np(ipmv, ipcode, nindptr).astype(np.int64)
    indices = _as_np(idxmv, 2, nelem)
    values = _as_np(dmv, dcode, nelem).astype(np.float64)
    b = _stream_builder(p, num_features=int(num_col))
    b.push_csr(indptr, indices, values, int(num_col))
    return lgb.Dataset(b, reference=ref, params=p, free_raw_data=False)


def dataset_from_csc(cpmv, cpcode, idxmv, dmv, dcode, ncol_ptr, nelem,
                     num_row, params, ref):
    p = _params(params)
    col_ptr = _as_np(cpmv, cpcode, ncol_ptr).astype(np.int64)
    indices = _as_np(idxmv, 2, nelem)
    values = _as_np(dmv, dcode, nelem).astype(np.float64)
    b = _stream_builder(p, num_features=len(col_ptr) - 1)
    b.push_csc(col_ptr, indices, values, int(num_row))
    return lgb.Dataset(b, reference=ref, params=p, free_raw_data=False)


def dataset_by_reference(ref, num_total_row):
    ref.construct()
    p = dict(ref.params)
    b = _stream_builder(p, reference=ref, num_total_rows=int(num_total_row))
    return lgb.Dataset(b, reference=ref, params=p, free_raw_data=False)


def dataset_push_rows(ds, mv, dcode, nrow, ncol, start_row):
    a = _as_np(mv, dcode, nrow * ncol).astype(np.float64)
    ds.push_rows(a.reshape(nrow, ncol), start_row=int(start_row))


def dataset_push_rows_csr(ds, ipmv, ipcode, idxmv, dmv, dcode, nindptr,
                          nelem, num_col, start_row):
    indptr = _as_np(ipmv, ipcode, nindptr).astype(np.int64)
    indices = _as_np(idxmv, 2, nelem)
    values = _as_np(dmv, dcode, nelem).astype(np.float64)
    ds.push_rows_csr(indptr, indices, values, int(num_col),
                     start_row=int(start_row))


def dataset_get_subset(ds, idxmv, n, params):
    idx = np.frombuffer(idxmv, dtype=np.int32, count=n).astype(np.int64)
    ds.construct()
    return lgb.Dataset._from_binned(ds.binned.subset(idx),
                                    params=_params(params) or dict(ds.params))


def dataset_save_binary(ds, fname):
    ds.construct()
    ds.save_binary(fname)


def dataset_dump_text(ds, fname):
    # reference LGBM_DatasetDumpText, adapted content: the dump shows
    # what training actually consumes — the post-bundling integer bin
    # matrix — under a small self-describing header
    ds.construct()
    b = ds.binned
    with open(fname, 'w') as fh:
        fh.write('num_data: %d\n' % int(b.num_data))
        fh.write('num_features: %d\n' % int(b.num_total_features))
        fh.write('feature_names: %s\n' % ','.join(b.feature_names))
        fh.write('num_bins: %s\n'
                 % ','.join(str(int(m.num_bin)) for m in b.bin_mappers))
        fh.write('storage_rows: %d\n' % int(b.bins.shape[0]))
        fh.write('has_label: %d\n'
                 % (0 if b.metadata.label is None else 1))
        fh.write('bin_data:\n')
        np.savetxt(fh, b.bins[:, :int(b.num_data)].T, fmt='%d')


def dataset_set_feature_names(ds, names):
    ds.set_feature_name([str(s) for s in names])


def dataset_feature_names(ds):
    ds.construct()
    return [str(s) for s in ds.binned.feature_names]


def dataset_num_data(ds):
    ds.construct()
    return int(ds.num_data())


def dataset_num_feature(ds):
    ds.construct()
    return int(ds.num_feature())


def booster_create(ds, params):
    return lgb.Booster(params=_params(params), train_set=ds)


def booster_add_valid(bst, ds, name):
    bst.add_valid(ds, name)


def booster_update(bst):
    return 1 if bst.update() else 0


def booster_update_custom(bst, gmv, hmv, n):
    g = np.frombuffer(gmv, dtype=np.float32, count=n).copy()
    h = np.frombuffer(hmv, dtype=np.float32, count=n).copy()
    return 1 if bst.update(fobj=lambda preds, ds: (g, h)) else 0


def booster_rollback(bst):
    bst.rollback_one_iter()


def booster_reset_parameter(bst, params):
    bst.reset_parameter(_params(params))


def booster_refit(bst, mv, lmv, nrow, ncol):
    X = np.frombuffer(mv, dtype=np.float64).reshape(nrow, ncol)
    y = np.frombuffer(lmv, dtype=np.float32, count=nrow).astype(np.float64)
    return bst.refit(np.array(X, copy=True), y)


def booster_current_iteration(bst):
    return int(bst.current_iteration())


def booster_model_string(bst, num_iteration):
    return bst.model_to_string(num_iteration=num_iteration)


def booster_get_eval(bst, data_idx):
    res = bst.eval_train() if data_idx == 0 else bst.eval_valid()
    if data_idx > 0:
        names = []
        for r in res:
            if r[0] not in names:
                names.append(r[0])
        if data_idx - 1 >= len(names):
            raise IndexError('data_idx %d out of range' % data_idx)
        want = names[data_idx - 1]
        res = [r for r in res if r[0] == want]
    return [float(r[2]) for r in res]


def booster_eval_names(bst):
    return [str(m.name) for m in bst._engine.train_metrics]


def booster_inner_predict(bst, data_idx):
    # reference GBDT::GetPredictAt: the scores the engine already
    # maintains for the training data (idx 0) or a validation set, with
    # the objective transform applied, laid out class-major [K*N]
    bst._drain()
    if data_idx == 0:
        raw = np.asarray(bst._engine.raw_train_score(), dtype=np.float64)
    else:
        n_valid = len(bst._valid_data)
        if data_idx - 1 >= n_valid:
            raise IndexError('data_idx %d out of range (%d valid sets)'
                             % (data_idx, n_valid))
        raw = np.asarray(bst._engine.raw_valid_score(data_idx - 1),
                         dtype=np.float64)
    obj = bst._objective
    if obj is not None:
        conv = np.asarray(obj.convert_output(raw.T), dtype=np.float64)
        raw = conv.T if conv.ndim == 2 else conv.reshape(1, -1)
    raw = np.ascontiguousarray(raw, dtype=np.float64)
    return (raw.tobytes(), int(raw.size))


def booster_grad_len(bst):
    ds = bst.train_set
    ds.construct()
    k = getattr(bst._engine, 'num_tree_per_iteration', 1)
    return int(ds.num_data()) * int(k)


def network_init(machines, local_listen_port, num_machines):
    if num_machines <= 1:
        return 0
    return int(lgb.init_distributed(machines=machines,
                                    local_listen_port=local_listen_port)
               or 0)
)PY";

PyObject* g_helpers = nullptr;  // module dict holding the helpers
std::once_flag g_py_once;
bool g_py_ok = false;
void InitPython();

std::string PyErrString() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

void InitPython() {
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized = true;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  // make the package importable: LIGHTGBM_TPU_ROOT wins, then the repo
  // root next to this shared library (the parent of the cpp/ dir the .so
  // lives in, located via dladdr); a pip install resolves through the
  // normal sys.path instead.  The candidate paths travel as REAL Python
  // objects (PyUnicode_DecodeFSDefault + PySys_SetObject), never spliced
  // into source text — a quote run or trailing backslash in a path must
  // stay path data, not become code inside the embedded interpreter.
  {
    PyObject* cands = PyList_New(0);
    auto append_path = [&](const std::string& p) {
      PyObject* s = PyUnicode_DecodeFSDefault(p.c_str());
      if (s != nullptr) {
        PyList_Append(cands, s);
        Py_DECREF(s);
      } else {
        PyErr_Clear();  // undecodable path: skip the candidate
      }
    };
    const char* env_root = std::getenv("LIGHTGBM_TPU_ROOT");
    if (env_root != nullptr) append_path(env_root);
    Dl_info info;
    if (dladdr(reinterpret_cast<void*>(&InitPython), &info) != 0 &&
        info.dli_fname != nullptr) {
      std::string so(info.dli_fname);
      auto cut = so.find_last_of('/');
      if (cut != std::string::npos) {
        std::string so_dir = so.substr(0, cut);
        auto cut2 = so_dir.find_last_of('/');
        if (cut2 != std::string::npos) append_path(so_dir.substr(0, cut2));
      }
    }
    PySys_SetObject("_lgbm_tpu_path_candidates", cands);
    Py_DECREF(cands);
    PyRun_SimpleString(
        "import os, sys\n"
        "for _cand in sys._lgbm_tpu_path_candidates:\n"
        "    if _cand and os.path.isdir(_cand) and _cand not in sys.path:\n"
        "        sys.path.insert(0, _cand)\n"
        "del sys._lgbm_tpu_path_candidates\n");
  }
  PyObject* mod = PyModule_New("_lgbm_tpu_c_helpers");
  PyObject* mdict = PyModule_GetDict(mod);
  PyDict_SetItemString(mdict, "__builtins__", PyEval_GetBuiltins());
  PyObject* res = PyRun_String(kHelperSource, Py_file_input, mdict, mdict);
  if (res == nullptr) {
    SetLastError("failed to initialize embedded training helpers: " +
                 PyErrString());
    Py_DECREF(mod);
  } else {
    Py_DECREF(res);
    g_helpers = mod;  // keep the module (and its dict) alive forever
    g_py_ok = true;
  }
  PyGILState_Release(g);
  if (we_initialized) {
    // release the GIL acquired by Py_Initialize so other threads can use
    // PyGILState_Ensure; the interpreter stays alive for the process
    PyEval_SaveThread();
  }
}

// RAII: ensure interpreter + helpers + GIL for the current scope.
struct PyScope {
  PyGILState_STATE g;
  bool ok;
  PyScope() : ok(false) {
    std::call_once(g_py_once, InitPython);
    if (!g_py_ok) return;
    g = PyGILState_Ensure();
    ok = true;
  }
  ~PyScope() {
    if (ok) PyGILState_Release(g);
  }
};

PyObject* Helper(const char* name) {
  return PyObject_GetAttrString(g_helpers, name);
}

// Call helpers[name](*args) with a fresh reference result; nullptr on
// error (message recorded).
PyObject* CallHelper(const char* name, PyObject* args) {
  PyObject* fn = Helper(name);
  PyObject* out = nullptr;
  if (fn != nullptr) {
    out = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
  }
  if (out == nullptr) SetLastError(std::string(name) + ": " + PyErrString());
  Py_XDECREF(args);
  return out;
}

int FailPy(const char* where) {
  SetLastError(std::string(where) + ": " + PyErrString());
  PyErr_Clear();
  return -1;
}

TrainBooster* AsTrain(BoosterHandle h) { return static_cast<TrainBooster*>(h); }
TrainDataset* AsDataset(DatasetHandle h) {
  if (HandleMagic(h) != kTrainDatasetMagic) return nullptr;
  return static_cast<TrainDataset*>(h);
}

// Returns the current native model with tb->model_mu held SHARED (see
// TrainHooks::booster_native); nullptr on error (nothing held).
void* TrainBoosterNative(void* h) {
  TrainBooster* tb = AsTrain(h);
  {
    // serialize the parse-and-swap: two concurrent first-predicts must
    // not both parse-and-free (use-after-free / double-free); after the
    // winner syncs, the loser sees !dirty and reuses the cache
    std::lock_guard<std::mutex> sync(tb->sync_mu);
    if (tb->dirty.load() || tb->native == nullptr) {
      PyScope py;
      if (!py.ok) return nullptr;
      PyObject* s = CallHelper("booster_model_string",
                               Py_BuildValue("(Oi)", tb->bst, -1));
      if (s == nullptr) return nullptr;
      const char* text = PyUnicode_AsUTF8(s);
      void* fresh = nullptr;
      int num_iter = 0;
      int rc = text == nullptr
                   ? -1
                   : LGBM_BoosterLoadModelFromString(text, &num_iter, &fresh);
      Py_DECREF(s);
      if (rc != 0) return nullptr;
      {
        // the free waits for every in-flight reader of the OLD model
        std::unique_lock<std::shared_mutex> w(tb->model_mu);
        if (tb->native != nullptr) LGBM_BoosterFree(tb->native);
        tb->native = fresh;
      }
      tb->dirty.store(false);
    }
  }
  // reader lock for the caller's whole predict/save; a resync triggered
  // by a concurrent update blocks at the unique_lock above until released
  tb->model_mu.lock_shared();
  void* native = tb->native;
  if (native == nullptr) {  // raced a failed resync
    tb->model_mu.unlock_shared();
    SetLastError("native model cache is empty");
  }
  return native;
}

void TrainBoosterNativeRelease(void* h) {
  AsTrain(h)->model_mu.unlock_shared();
}

int TrainBoosterFree(void* h) {
  TrainBooster* tb = AsTrain(h);
  if (tb->native != nullptr) LGBM_BoosterFree(tb->native);
  if (tb->bst != nullptr) {
    PyScope py;
    if (py.ok) Py_DECREF(tb->bst);
  }
  delete tb;
  return 0;
}

int TrainBoosterCurrentIteration(void* h, int* out) {
  PyScope py;
  if (!py.ok) return -1;
  PyObject* r = CallHelper("booster_current_iteration",
                           Py_BuildValue("(O)", AsTrain(h)->bst));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// registered into the base library when this library loads
const lgbm_tpu_internal::TrainHooks g_hooks = {
    &TrainBoosterNative, &TrainBoosterNativeRelease, &TrainBoosterFree,
    &TrainBoosterCurrentIteration};

__attribute__((constructor)) void RegisterHooks() {
  lgbm_tpu_internal::RegisterTrainHooks(&g_hooks);
}

}  // namespace

extern "C" {

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               DatasetHandle reference, DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* ref = AsDataset(reference);
  PyObject* r = CallHelper(
      "dataset_from_file",
      Py_BuildValue("(ssO)", filename, parameters ? parameters : "",
                    ref ? ref->ds : Py_None));
  if (r == nullptr) return -1;
  TrainDataset* d = new TrainDataset;
  d->ds = r;
  *out = d;
  return 0;
}

int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters, DatasetHandle reference,
                              DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  if (data_type != C_API_DTYPE_FLOAT32 && data_type != C_API_DTYPE_FLOAT64) {
    SetLastError("data_type must be float32/float64");
    return -1;
  }
  Py_ssize_t esz = data_type == C_API_DTYPE_FLOAT32 ? 4 : 8;
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      static_cast<Py_ssize_t>(nrow) * ncol * esz, PyBUF_READ);
  if (mv == nullptr) return FailPy("LGBM_DatasetCreateFromMat");
  TrainDataset* ref = AsDataset(reference);
  PyObject* r = CallHelper(
      "dataset_from_mat",
      Py_BuildValue("(NiiiisO)", mv, data_type, nrow, ncol, is_row_major,
                    parameters ? parameters : "", ref ? ref->ds : Py_None));
  if (r == nullptr) return -1;
  TrainDataset* d = new TrainDataset;
  d->ds = r;
  *out = d;
  return 0;
}

namespace {

// read-only memoryview over a C buffer; nullptr on failure
PyObject* MemView(const void* p, Py_ssize_t bytes) {
  return PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(p)), bytes, PyBUF_READ);
}

Py_ssize_t DTypeSize(int code) {
  return (code == C_API_DTYPE_FLOAT64 || code == C_API_DTYPE_INT64) ? 8 : 4;
}

bool CheckIntCode(int code, const char* what) {
  if (code != C_API_DTYPE_INT32 && code != C_API_DTYPE_INT64) {
    SetLastError(std::string(what) + " must be C_API_DTYPE_INT32/INT64");
    return false;
  }
  return true;
}

bool CheckFloatCode(int code, const char* what) {
  if (code != C_API_DTYPE_FLOAT32 && code != C_API_DTYPE_FLOAT64) {
    SetLastError(std::string(what) + " must be float32/float64");
    return false;
  }
  return true;
}

// shared CSR marshalling for CreateFromCSR / PushRowsByCSR: builds the
// three memoryviews or records an error and returns false
bool CsrViews(const void* indptr, int indptr_type, const int32_t* indices,
              const void* data, int data_type, int64_t nindptr,
              int64_t nelem, PyObject** ipmv, PyObject** idxmv,
              PyObject** dmv, const char* what) {
  if (!CheckIntCode(indptr_type, "indptr_type") ||
      !CheckFloatCode(data_type, "data_type"))
    return false;
  *ipmv = MemView(indptr, nindptr * DTypeSize(indptr_type));
  *idxmv = MemView(indices, nelem * 4);
  *dmv = MemView(data, nelem * DTypeSize(data_type));
  if (*ipmv == nullptr || *idxmv == nullptr || *dmv == nullptr) {
    Py_XDECREF(*ipmv);
    Py_XDECREF(*idxmv);
    Py_XDECREF(*dmv);
    SetLastError(std::string(what) + ": cannot wrap input buffers");
    PyErr_Clear();
    return false;
  }
  return true;
}

int WrapNewDataset(PyObject* r, DatasetHandle* out) {
  if (r == nullptr) return -1;
  TrainDataset* d = new TrainDataset;
  d->ds = r;
  *out = d;
  return 0;
}

}  // namespace

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              DatasetHandle reference, DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  PyObject *ipmv, *idxmv, *dmv;
  if (!CsrViews(indptr, indptr_type, indices, data, data_type, nindptr,
                nelem, &ipmv, &idxmv, &dmv, "LGBM_DatasetCreateFromCSR"))
    return -1;
  TrainDataset* ref = AsDataset(reference);
  PyObject* r = CallHelper(
      "dataset_from_csr",
      Py_BuildValue("(NiNNiLLLsO)", ipmv, indptr_type, idxmv, dmv, data_type,
                    static_cast<long long>(nindptr),
                    static_cast<long long>(nelem),
                    static_cast<long long>(num_col),
                    parameters ? parameters : "",
                    ref ? ref->ds : Py_None));
  return WrapNewDataset(r, out);
}

int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, const char* parameters,
                              DatasetHandle reference, DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  PyObject *cpmv, *idxmv, *dmv;
  if (!CsrViews(col_ptr, col_ptr_type, indices, data, data_type, ncol_ptr,
                nelem, &cpmv, &idxmv, &dmv, "LGBM_DatasetCreateFromCSC"))
    return -1;
  TrainDataset* ref = AsDataset(reference);
  PyObject* r = CallHelper(
      "dataset_from_csc",
      Py_BuildValue("(NiNNiLLLsO)", cpmv, col_ptr_type, idxmv, dmv, data_type,
                    static_cast<long long>(ncol_ptr),
                    static_cast<long long>(nelem),
                    static_cast<long long>(num_row),
                    parameters ? parameters : "",
                    ref ? ref->ds : Py_None));
  return WrapNewDataset(r, out);
}

int LGBM_DatasetCreateByReference(DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* ref = AsDataset(reference);
  if (ref == nullptr) {
    SetLastError("LGBM_DatasetCreateByReference needs a dataset handle "
                 "as reference");
    return -1;
  }
  PyObject* r = CallHelper(
      "dataset_by_reference",
      Py_BuildValue("(OL)", ref->ds, static_cast<long long>(num_total_row)));
  return WrapNewDataset(r, out);
}

int LGBM_DatasetPushRows(DatasetHandle dataset, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(dataset);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  if (!CheckFloatCode(data_type, "data_type")) return -1;
  PyObject* mv = MemView(data, static_cast<Py_ssize_t>(nrow) * ncol *
                                   DTypeSize(data_type));
  if (mv == nullptr) return FailPy("LGBM_DatasetPushRows");
  PyObject* r = CallHelper(
      "dataset_push_rows",
      Py_BuildValue("(ONiiii)", d->ds, mv, data_type, nrow, ncol, start_row));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetPushRowsByCSR(DatasetHandle dataset, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(dataset);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject *ipmv, *idxmv, *dmv;
  if (!CsrViews(indptr, indptr_type, indices, data, data_type, nindptr,
                nelem, &ipmv, &idxmv, &dmv, "LGBM_DatasetPushRowsByCSR"))
    return -1;
  PyObject* r = CallHelper(
      "dataset_push_rows_csr",
      Py_BuildValue("(ONiNNiLLLL)", d->ds, ipmv, indptr_type, idxmv, dmv,
                    data_type, static_cast<long long>(nindptr),
                    static_cast<long long>(nelem),
                    static_cast<long long>(num_col),
                    static_cast<long long>(start_row)));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetSubset(DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* mv = MemView(used_row_indices,
                         static_cast<Py_ssize_t>(num_used_row_indices) * 4);
  if (mv == nullptr) return FailPy("LGBM_DatasetGetSubset");
  PyObject* r = CallHelper(
      "dataset_get_subset",
      Py_BuildValue("(ONis)", d->ds, mv, num_used_row_indices,
                    parameters ? parameters : ""));
  return WrapNewDataset(r, out);
}

int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper("dataset_save_binary",
                           Py_BuildValue("(Os)", d->ds, filename));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetDumpText(DatasetHandle handle, const char* filename) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper("dataset_dump_text",
                           Py_BuildValue("(Os)", d->ds, filename));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* names = PyList_New(0);
  for (int i = 0; i < num_feature_names; ++i) {
    PyObject* s = PyUnicode_DecodeFSDefault(
        feature_names[i] != nullptr ? feature_names[i] : "");
    if (s == nullptr) {
      Py_DECREF(names);
      return FailPy("LGBM_DatasetSetFeatureNames");
    }
    PyList_Append(names, s);
    Py_DECREF(s);
  }
  PyObject* r = CallHelper("dataset_set_feature_names",
                           Py_BuildValue("(ON)", d->ds, names));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetFeatureNames(DatasetHandle handle, char** feature_names,
                                int* num_feature_names) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper("dataset_feature_names",
                           Py_BuildValue("(O)", d->ds));
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  *num_feature_names = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* name = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    // 128-byte caller buffers (the GetEvalNames contract)
    std::strncpy(feature_names[i], name != nullptr ? name : "", 127);
    feature_names[i][127] = '\0';
  }
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  Py_ssize_t esz = (type == C_API_DTYPE_FLOAT64 || type == C_API_DTYPE_INT64)
                       ? 8
                       : 4;
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(field_data)),
      static_cast<Py_ssize_t>(num_element) * esz, PyBUF_READ);
  if (mv == nullptr) return FailPy("LGBM_DatasetSetField");
  PyObject* r = CallHelper(
      "dataset_set_field",
      Py_BuildValue("(OsNi)", d->ds, field_name, mv, type));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr,
                         int* out_type) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper(
      "dataset_get_field",
      Py_BuildValue("(Os)", d->ds, field_name ? field_name : ""));
  if (r == nullptr) return -1;
  PyObject* bytes_obj = PyTuple_GetItem(r, 0);
  char* buf = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(bytes_obj, &buf, &nbytes) != 0) {
    Py_DECREF(r);
    return FailPy("LGBM_DatasetGetField");
  }
  d->field_buf.assign(buf, static_cast<size_t>(nbytes));
  *out_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
  *out_len = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 2)));
  *out_ptr = d->field_buf.data();
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetFeatureNumBin(DatasetHandle handle, int feature_idx,
                                 int32_t* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper("dataset_feature_num_bin",
                           Py_BuildValue("(Oi)", d->ds, feature_idx));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data,
                               int data_type, int32_t* nrow, int32_t ncol,
                               int is_row_major, const char* parameters,
                               DatasetHandle reference,
                               DatasetHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  if (nmat <= 0 || data == nullptr || nrow == nullptr) {
    SetLastError("LGBM_DatasetCreateFromMats needs nmat > 0 blocks");
    return -1;
  }
  if (!CheckFloatCode(data_type, "data_type")) return -1;
  Py_ssize_t esz = DTypeSize(data_type);
  PyObject* mvs = PyList_New(0);
  PyObject* rows = PyList_New(0);
  for (int32_t i = 0; i < nmat; ++i) {
    PyObject* mv = MemView(
        data[i], static_cast<Py_ssize_t>(nrow[i]) * ncol * esz);
    if (mv == nullptr) {
      Py_DECREF(mvs);
      Py_DECREF(rows);
      return FailPy("LGBM_DatasetCreateFromMats");
    }
    PyList_Append(mvs, mv);
    Py_DECREF(mv);
    PyObject* n = PyLong_FromLong(nrow[i]);
    PyList_Append(rows, n);
    Py_DECREF(n);
  }
  TrainDataset* ref = AsDataset(reference);
  PyObject* r = CallHelper(
      "dataset_from_mats",
      Py_BuildValue("(NiNiisO)", mvs, data_type, rows, ncol, is_row_major,
                    parameters ? parameters : "",
                    ref ? ref->ds : Py_None));
  return WrapNewDataset(r, out);
}

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper("dataset_num_data", Py_BuildValue("(O)", d->ds));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) {
    SetLastError("not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper("dataset_num_feature",
                           Py_BuildValue("(O)", d->ds));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetFree(DatasetHandle handle) {
  TrainDataset* d = AsDataset(handle);
  if (d == nullptr) return 0;
  PyScope py;
  if (py.ok) Py_XDECREF(d->ds);
  delete d;
  return 0;
}

int LGBM_BoosterCreate(DatasetHandle train_data, const char* parameters,
                       BoosterHandle* out) {
  PyScope py;
  if (!py.ok) return -1;
  TrainDataset* d = AsDataset(train_data);
  if (d == nullptr) {
    SetLastError("train_data is not a dataset handle");
    return -1;
  }
  PyObject* r = CallHelper(
      "booster_create",
      Py_BuildValue("(Os)", d->ds, parameters ? parameters : ""));
  if (r == nullptr) return -1;
  TrainBooster* b = new TrainBooster;
  b->bst = r;
  *out = b;
  return 0;
}

int LGBM_BoosterAddValidData(BoosterHandle handle, DatasetHandle valid_data) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  TrainDataset* d = AsDataset(valid_data);
  if (d == nullptr) {
    SetLastError("valid_data is not a dataset handle");
    return -1;
  }
  TrainBooster* tb = AsTrain(handle);
  PyObject* r = CallHelper("booster_add_valid",
                           Py_BuildValue("(OOs)", tb->bst, d->ds, "valid"));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  TrainBooster* tb = AsTrain(handle);
  PyObject* r = CallHelper("booster_update", Py_BuildValue("(O)", tb->bst));
  if (r == nullptr) return -1;
  if (is_finished) *is_finished = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  tb->dirty = true;
  return 0;
}

int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int* is_finished) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  TrainBooster* tb = AsTrain(handle);
  // gradient length = num_data * num_class, resolved on the python side
  PyObject* nobj = CallHelper("booster_grad_len",
                              Py_BuildValue("(O)", tb->bst));
  if (nobj == nullptr) return -1;
  long n = PyLong_AsLong(nobj);
  Py_DECREF(nobj);
  if (n <= 0) {
    SetLastError("cannot determine gradient length for custom update");
    return -1;
  }
  Py_ssize_t bytes = static_cast<Py_ssize_t>(n) * 4;
  PyObject* gmv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(grad)), bytes,
      PyBUF_READ);
  PyObject* hmv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(hess)), bytes,
      PyBUF_READ);
  if (gmv == nullptr || hmv == nullptr) {
    Py_XDECREF(gmv);
    Py_XDECREF(hmv);
    return FailPy("LGBM_BoosterUpdateOneIterCustom");
  }
  PyObject* r = CallHelper(
      "booster_update_custom",
      Py_BuildValue("(ONNl)", tb->bst, gmv, hmv, n));
  if (r == nullptr) return -1;
  if (is_finished) *is_finished = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  tb->dirty = true;
  return 0;
}

int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  TrainBooster* tb = AsTrain(handle);
  PyObject* r = CallHelper("booster_rollback", Py_BuildValue("(O)", tb->bst));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  tb->dirty = true;
  return 0;
}

int LGBM_BoosterResetParameter(BoosterHandle handle, const char* parameters) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  TrainBooster* tb = AsTrain(handle);
  PyObject* r = CallHelper(
      "booster_reset_parameter",
      Py_BuildValue("(Os)", tb->bst, parameters ? parameters : ""));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  // a parameter change (learning_rate, shrinkage) alters FUTURE trees,
  // not the saved model text, but resync anyway: the parameters block of
  // the model text records the live config
  tb->dirty = true;
  return 0;
}

int LGBM_BoosterRefit(BoosterHandle handle, const double* data,
                      const float* label, int32_t nrow, int32_t ncol) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  if (data == nullptr || label == nullptr || nrow <= 0 || ncol <= 0) {
    SetLastError("LGBM_BoosterRefit needs data, label and positive shape");
    return -1;
  }
  TrainBooster* tb = AsTrain(handle);
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(data)),
      static_cast<Py_ssize_t>(nrow) * ncol * 8, PyBUF_READ);
  PyObject* lmv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(label)),
      static_cast<Py_ssize_t>(nrow) * 4, PyBUF_READ);
  if (mv == nullptr || lmv == nullptr) {
    Py_XDECREF(mv);
    Py_XDECREF(lmv);
    return FailPy("LGBM_BoosterRefit");
  }
  PyObject* r = CallHelper("booster_refit",
                           Py_BuildValue("(ONNii)", tb->bst, mv, lmv,
                                         nrow, ncol));
  if (r == nullptr) return -1;
  // swap the handle's python booster to the refit result (under the GIL:
  // every other entry point touches tb->bst inside its own PyScope); the
  // native Model* cache resyncs lazily from the new model text
  PyObject* old = tb->bst;
  tb->bst = r;
  Py_DECREF(old);
  tb->dirty = true;
  return 0;
}

int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  PyObject* r = CallHelper(
      "booster_get_eval",
      Py_BuildValue("(Oi)", AsTrain(handle)->bst, data_idx));
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  *out_len = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    out_results[i] = PyFloat_AsDouble(PyList_GetItem(r, i));
  Py_DECREF(r);
  return 0;
}

// shared body of GetNumPredict/GetPredict: the helper returns
// (float64 bytes, count); out_result == nullptr fetches the size only
static int InnerPredict(BoosterHandle handle, int data_idx, int64_t* out_len,
                        double* out_result, const char* where) {
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError(std::string(where) +
                 ": inner prediction buffers exist on training boosters "
                 "only (a loaded model has no attached data)");
    return -1;
  }
  PyScope py;
  if (!py.ok) return -1;
  PyObject* r = CallHelper(
      "booster_inner_predict",
      Py_BuildValue("(Oi)", AsTrain(handle)->bst, data_idx));
  if (r == nullptr) return -1;
  PyObject* bytes = PyTuple_GetItem(r, 0);
  int64_t n = PyLong_AsLongLong(PyTuple_GetItem(r, 1));
  if (out_len) *out_len = n;
  if (out_result != nullptr && n > 0) {
    char* buf = nullptr;
    Py_ssize_t blen = 0;
    if (PyBytes_AsStringAndSize(bytes, &buf, &blen) != 0 ||
        blen != static_cast<Py_ssize_t>(n * sizeof(double))) {
      Py_DECREF(r);
      SetLastError(std::string(where) + ": score buffer size mismatch");
      return -1;
    }
    std::memcpy(out_result, buf, blen);
  }
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len) {
  return InnerPredict(handle, data_idx, out_len, nullptr,
                      "LGBM_BoosterGetNumPredict");
}

int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result) {
  return InnerPredict(handle, data_idx, out_len, out_result,
                      "LGBM_BoosterGetPredict");
}

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  PyObject* r = CallHelper("booster_eval_names",
                           Py_BuildValue("(O)", AsTrain(handle)->bst));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyList_Size(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEvalNames(BoosterHandle handle, int* out_len,
                             char** out_strs) {
  PyScope py;
  if (!py.ok) return -1;
  if (!lgbm_tpu_internal::IsTrainBooster(handle)) {
    SetLastError("not a training booster");
    return -1;
  }
  PyObject* r = CallHelper("booster_eval_names",
                           Py_BuildValue("(O)", AsTrain(handle)->bst));
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  *out_len = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* name = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    std::strcpy(out_strs[i], name != nullptr ? name : "");
  }
  Py_DECREF(r);
  return 0;
}

int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines) {
  (void)listen_time_out;  // XLA collectives own connection management
  PyScope py;
  if (!py.ok) return -1;
  PyObject* r = CallHelper(
      "network_init",
      Py_BuildValue("(sii)", machines ? machines : "", local_listen_port,
                    num_machines));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_NetworkFree() {
  // jax.distributed teardown happens at process exit; matching the
  // reference's idempotent Network::Dispose contract
  return 0;
}

}  // extern "C"
