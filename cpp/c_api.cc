// Native model runtime behind lightgbm_tpu_c_api.h.
//
// Reimplements, in dependency-free C++17, the prediction side of the
// reference stack: the text-model parser (gbdt_model_text.cpp
// LoadModelFromString / Tree(const char*)), tree traversal with the
// decision_type bit layout (tree.h:14-15 — bit0 categorical, bit1
// default_left, bits 2-3 missing type), and the objective output
// transforms (ConvertOutput of binary/multiclass/regression families).
// Numerics follow the same rules as the Python predictor
// (lightgbm_tpu/models/tree.py) so all three agree bit-for-bit.

#include "lightgbm_tpu_c_api.h"
#include "c_internal.h"

#ifdef _OPENMP
#include <omp.h>
#endif

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

thread_local std::string g_last_error;

constexpr double kZeroThreshold = 1e-35;  // reference meta.h

constexpr int kCategoricalMask = 1;
constexpr int kDefaultLeftMask = 2;
constexpr int kMissingNone = 0;
constexpr int kMissingZero = 1;
constexpr int kMissingNan = 2;

struct Tree {
  int num_leaves = 1;
  int num_cat = 0;
  double shrinkage = 1.0;
  std::vector<int> split_feature;
  std::vector<double> threshold;
  std::vector<int> decision_type;
  std::vector<int> left_child;
  std::vector<int> right_child;
  std::vector<double> leaf_value;
  std::vector<int64_t> cat_boundaries;
  std::vector<uint32_t> cat_threshold;
  // importance/dump extras (empty-tolerant: old model strings without
  // these lines still load and predict)
  std::vector<double> split_gain;
  std::vector<double> internal_value;
  std::vector<int64_t> internal_count;
  std::vector<int64_t> leaf_count;

  bool CategoricalDecision(double fval, int node) const {
    int mt = (decision_type[node] >> 2) & 3;
    int cat;
    if (std::isnan(fval)) {
      if (mt == kMissingNan) return false;  // NaN goes right
      cat = 0;
    } else {
      cat = static_cast<int>(fval);
      if (cat < 0) return false;
    }
    int ci = static_cast<int>(threshold[node]);
    int64_t lo = cat_boundaries[ci], hi = cat_boundaries[ci + 1];
    int64_t i1 = lo + cat / 32;
    if (i1 >= hi) return false;
    return (cat_threshold[i1] >> (cat % 32)) & 1;
  }

  bool NumericalDecision(double fval, int node) const {
    int dt = decision_type[node];
    int mt = (dt >> 2) & 3;
    bool is_nan = std::isnan(fval);
    if (is_nan && mt != kMissingNan) fval = 0.0;
    bool missing = (mt == kMissingZero && std::fabs(fval) <= kZeroThreshold) ||
                   (mt == kMissingNan && is_nan);
    if (missing) return (dt & kDefaultLeftMask) != 0;
    return fval <= threshold[node];
  }

  // returns ~leaf_index reached by the row
  int TraverseNode(const double* row) const {
    if (num_leaves <= 1) return ~0;
    int node = 0;
    while (node >= 0) {
      double fval = row[split_feature[node]];
      bool left = (decision_type[node] & kCategoricalMask)
                      ? CategoricalDecision(fval, node)
                      : NumericalDecision(fval, node);
      node = left ? left_child[node] : right_child[node];
    }
    return node;
  }

  double Predict(const double* row) const { return leaf_value[~TraverseNode(row)]; }
  int PredictLeafIndex(const double* row) const { return ~TraverseNode(row); }
};

enum class Transform {
  kNone,
  kSigmoid,      // binary / multiclassova / xentropy: 1/(1+exp(-s*x))
  kSoftmax,      // multiclass
  kExp,          // poisson / gamma / tweedie
  kSignSquare,   // regression with sqrt
  kLog1pExp,     // xentlambda
};

struct Model {
  const uint32_t magic = lgbm_tpu_internal::kNativeBoosterMagic;
  int num_class = 1;
  int num_tree_per_iteration = 1;
  int max_feature_idx = 0;
  bool average_output = false;
  double sigmoid = 1.0;
  Transform transform = Transform::kNone;
  std::string objective;
  std::vector<std::string> feature_names;
  std::vector<Tree> trees;
  std::string text;  // original model text, for SaveModel

  int NumIterations() const {
    if (num_tree_per_iteration <= 0) return static_cast<int>(trees.size());
    return static_cast<int>(trees.size()) / num_tree_per_iteration;
  }
};

std::vector<std::string> SplitWs(const std::string& s) {
  std::vector<std::string> out;
  std::istringstream is(s);
  std::string tok;
  while (is >> tok) out.push_back(tok);
  return out;
}

template <typename T>
std::vector<T> ParseArray(const std::string& s) {
  std::vector<T> out;
  std::istringstream is(s);
  double v;
  while (is >> v) out.push_back(static_cast<T>(v));
  return out;
}

void PickTransform(Model* m) {
  auto toks = SplitWs(m->objective);
  if (toks.empty()) return;
  const std::string& kind = toks[0];
  for (size_t i = 1; i < toks.size(); ++i) {
    if (toks[i].rfind("sigmoid:", 0) == 0)
      m->sigmoid = std::stod(toks[i].substr(8));
  }
  bool sqrt = std::find(toks.begin() + 1, toks.end(), "sqrt") != toks.end();
  if (kind == "binary" || kind == "multiclassova" ||
      kind == "cross_entropy" || kind == "xentropy") {
    m->transform = Transform::kSigmoid;
    if (kind == "cross_entropy" || kind == "xentropy") m->sigmoid = 1.0;
  } else if (kind == "multiclass" || kind == "softmax") {
    m->transform = Transform::kSoftmax;
  } else if (kind == "poisson" || kind == "gamma" || kind == "tweedie") {
    m->transform = Transform::kExp;
  } else if (kind == "cross_entropy_lambda" || kind == "xentlambda") {
    m->transform = Transform::kLog1pExp;
  } else if (sqrt) {
    m->transform = Transform::kSignSquare;
  }
}

bool ParseModel(const std::string& text, Model* m, std::string* err) {
  m->text = text;
  std::istringstream is(text);
  std::string line;
  bool in_tree = false;
  std::unordered_map<std::string, std::string> tree_kv;

  auto finish_tree = [&]() -> bool {
    if (!in_tree) return true;
    Tree t;
    auto get = [&](const char* k) -> const std::string& {
      static const std::string empty;
      auto it = tree_kv.find(k);
      return it == tree_kv.end() ? empty : it->second;
    };
    t.num_leaves = std::max(1, atoi(get("num_leaves").c_str()));
    t.num_cat = atoi(get("num_cat").c_str());
    if (!get("shrinkage").empty()) t.shrinkage = std::stod(get("shrinkage"));
    t.leaf_value = ParseArray<double>(get("leaf_value"));
    if (t.num_leaves > 1) {
      t.split_feature = ParseArray<int>(get("split_feature"));
      t.threshold = ParseArray<double>(get("threshold"));
      t.decision_type = ParseArray<int>(get("decision_type"));
      t.left_child = ParseArray<int>(get("left_child"));
      t.right_child = ParseArray<int>(get("right_child"));
      size_t ni = static_cast<size_t>(t.num_leaves - 1);
      if (t.split_feature.size() != ni || t.threshold.size() != ni ||
          t.left_child.size() != ni || t.right_child.size() != ni ||
          t.leaf_value.size() != static_cast<size_t>(t.num_leaves)) {
        *err = "tree arrays disagree with num_leaves";
        return false;
      }
      if (t.decision_type.empty()) t.decision_type.assign(ni, 0);
      if (t.num_cat > 0) {
        t.cat_boundaries = ParseArray<int64_t>(get("cat_boundaries"));
        t.cat_threshold = ParseArray<uint32_t>(get("cat_threshold"));
      }
      t.split_gain = ParseArray<double>(get("split_gain"));
      t.internal_value = ParseArray<double>(get("internal_value"));
      t.internal_count = ParseArray<int64_t>(get("internal_count"));
      t.leaf_count = ParseArray<int64_t>(get("leaf_count"));
    }
    m->trees.push_back(std::move(t));
    in_tree = false;
    tree_kv.clear();
    return true;
  };

  while (std::getline(is, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line == "end of trees") break;
    if (line.rfind("Tree=", 0) == 0) {
      if (!finish_tree()) return false;
      in_tree = true;
      tree_kv.clear();
      continue;
    }
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = line.substr(0, eq), val = line.substr(eq + 1);
    if (in_tree) {
      tree_kv[key] = val;
      continue;
    }
    if (key == "num_class") m->num_class = atoi(val.c_str());
    else if (key == "num_tree_per_iteration")
      m->num_tree_per_iteration = atoi(val.c_str());
    else if (key == "max_feature_idx") m->max_feature_idx = atoi(val.c_str());
    else if (key == "objective") m->objective = val;
    else if (key == "feature_names") m->feature_names = SplitWs(val);
    else if (key == "average_output") m->average_output = true;
  }
  if (!finish_tree()) return false;
  if (m->trees.empty()) {
    *err = "no trees found in model";
    return false;
  }
  if (m->num_tree_per_iteration <= 0) m->num_tree_per_iteration = 1;
  PickTransform(m);
  return true;
}

void ApplyTransform(const Model& m, double* row_out) {
  int k = m.num_tree_per_iteration;
  switch (m.transform) {
    case Transform::kNone:
      break;
    case Transform::kSigmoid:
      for (int j = 0; j < k; ++j)
        row_out[j] = 1.0 / (1.0 + std::exp(-m.sigmoid * row_out[j]));
      break;
    case Transform::kSoftmax: {
      double mx = row_out[0];
      for (int j = 1; j < k; ++j) mx = std::max(mx, row_out[j]);
      double sum = 0.0;
      for (int j = 0; j < k; ++j) {
        row_out[j] = std::exp(row_out[j] - mx);
        sum += row_out[j];
      }
      for (int j = 0; j < k; ++j) row_out[j] /= sum;
      break;
    }
    case Transform::kExp:
      for (int j = 0; j < k; ++j) row_out[j] = std::exp(row_out[j]);
      break;
    case Transform::kSignSquare:
      for (int j = 0; j < k; ++j) {
        double v = row_out[j];
        row_out[j] = (v >= 0 ? v * v : -v * v);
      }
      break;
    case Transform::kLog1pExp:
      for (int j = 0; j < k; ++j) row_out[j] = std::log1p(std::exp(row_out[j]));
      break;
  }
}

int Fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

}  // namespace

namespace lgbm_tpu_internal {
void SetLastError(const std::string& msg) { g_last_error = msg; }

namespace {
const TrainHooks* g_train_hooks = nullptr;
}  // namespace

void RegisterTrainHooks(const TrainHooks* hooks) { g_train_hooks = hooks; }
const TrainHooks* GetTrainHooks() { return g_train_hooks; }
}  // namespace lgbm_tpu_internal

namespace {

// one row's scores/leaf-indices — shared by the dense and CSR entry points
void PredictRow(const Model& m, const double* row, int predict_type,
                int iters, int used_trees, double* out_row) {
  int k = m.num_tree_per_iteration;
  if (predict_type == C_API_PREDICT_LEAF_INDEX) {
    for (int t = 0; t < used_trees; ++t)
      out_row[t] = m.trees[t].PredictLeafIndex(row);
    return;
  }
  for (int j = 0; j < k; ++j) out_row[j] = 0.0;
  for (int t = 0; t < used_trees; ++t)
    out_row[t % k] += m.trees[t].Predict(row);
  if (m.average_output) {
    for (int j = 0; j < k; ++j) out_row[j] /= iters;
  } else if (predict_type == C_API_PREDICT_NORMAL) {
    ApplyTransform(m, out_row);
  }
}

// Resolve a public handle to a native Model*: training boosters (embedded
// Python, c_train.cc) are re-synced into their native model cache so every
// shared entry point below runs identical code for both booster kinds.
// RAII: for a training booster the hook returns with the handle's model
// lock held SHARED, so a concurrent UpdateOneIter->resync cannot free the
// model under an in-flight predict/save; the destructor releases it.
// Loaded boosters need no lock (the caller owns their lifetime).
struct ModelRef {
  Model* m = nullptr;
  void* locked = nullptr;  // the train handle whose shared lock we hold
  explicit ModelRef(BoosterHandle h) {
    if (lgbm_tpu_internal::IsTrainBooster(h)) {
      void* native = lgbm_tpu_internal::GetTrainHooks()->booster_native(h);
      if (native == nullptr) return;
      locked = h;
      m = static_cast<Model*>(native);
      return;
    }
    m = static_cast<Model*>(h);
  }
  ~ModelRef() {
    if (locked != nullptr)
      lgbm_tpu_internal::GetTrainHooks()->booster_native_release(locked);
  }
  ModelRef(const ModelRef&) = delete;
  ModelRef& operator=(const ModelRef&) = delete;
};

int LoadModel(const std::string& text, int* out_num_iterations,
              BoosterHandle* out) {
  auto m = std::make_unique<Model>();
  std::string err;
  if (!ParseModel(text, m.get(), &err)) return Fail("model parse error: " + err);
  if (out_num_iterations) *out_num_iterations = m->NumIterations();
  *out = m.release();
  return 0;
}

}  // namespace

extern "C" {

const char* LGBM_GetLastError() { return g_last_error.c_str(); }

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  std::ifstream f(filename);
  if (!f) return Fail(std::string("cannot open model file: ") + filename);
  std::stringstream ss;
  ss << f.rdbuf();
  return LoadModel(ss.str(), out_num_iterations, out);
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  if (model_str == nullptr) return Fail("model_str is null");
  return LoadModel(model_str, out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  if (lgbm_tpu_internal::IsTrainBooster(handle))
    return lgbm_tpu_internal::GetTrainHooks()->booster_free(handle);
  delete static_cast<Model*>(handle);
  return 0;
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  *out_len = m->num_class;
  return 0;
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  *out_len = m->max_feature_idx + 1;
  return 0;
}

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out_iteration) {
  if (lgbm_tpu_internal::IsTrainBooster(handle))
    return lgbm_tpu_internal::GetTrainHooks()->booster_current_iteration(
        handle, out_iteration);
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  *out_iteration = m->NumIterations();
  return 0;
}

int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                     int* out_tree_per_iteration) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  *out_tree_per_iteration = m->num_tree_per_iteration;
  return 0;
}

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  *out_models = static_cast<int>(m->trees.size());
  return 0;
}

int LGBM_BoosterGetFeatureNames(BoosterHandle handle, int* out_len,
                                char** out_strs) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  int nfeat = m->max_feature_idx + 1;
  for (int f = 0; f < nfeat; ++f) {
    std::string name = f < static_cast<int>(m->feature_names.size())
                           ? m->feature_names[f]
                           : "Column_" + std::to_string(f);
    // fixed 128-byte buffers, the GetEvalNames convention of this ABI
    std::snprintf(out_strs[f], 128, "%s", name.c_str());
  }
  *out_len = nfeat;
  return 0;
}

int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (tree_idx < 0 || tree_idx >= static_cast<int>(m->trees.size()))
    return Fail("tree_idx " + std::to_string(tree_idx) +
                " out of range for " + std::to_string(m->trees.size()) +
                " trees");
  const Tree& t = m->trees[tree_idx];
  if (leaf_idx < 0 || leaf_idx >= static_cast<int>(t.leaf_value.size()))
    return Fail("leaf_idx " + std::to_string(leaf_idx) +
                " out of range for " + std::to_string(t.leaf_value.size()) +
                " leaves");
  *out_val = t.leaf_value[leaf_idx];
  return 0;
}

namespace {

// Rewrite one leaf_value token of one tree block in the stored model
// text, so SaveModel/SaveModelToString round-trips carry the patch.
// Only the patched token is reformatted (%.17g round-trips doubles);
// every other byte of the text is preserved.
bool PatchLeafValueInText(std::string* text, int tree_idx, int leaf_idx,
                          double val) {
  size_t pos = 0;
  for (int seen = 0;; ++seen) {
    pos = text->find("Tree=", pos);
    if (pos == std::string::npos) return false;
    if (pos != 0 && (*text)[pos - 1] != '\n') {  // mid-line match
      pos += 5;
      --seen;
      continue;
    }
    if (seen == tree_idx) break;
    pos += 5;
  }
  size_t next_tree = text->find("\nTree=", pos);
  size_t lv = text->find("\nleaf_value=", pos);
  if (lv == std::string::npos || (next_tree != std::string::npos &&
                                  lv > next_tree))
    return false;
  size_t start = lv + strlen("\nleaf_value=");
  size_t end = text->find('\n', start);
  if (end == std::string::npos) end = text->size();
  std::vector<std::string> toks = SplitWs(text->substr(start, end - start));
  if (leaf_idx < 0 || leaf_idx >= static_cast<int>(toks.size()))
    return false;
  char buf[40];
  std::snprintf(buf, sizeof(buf), "%.17g", val);
  toks[leaf_idx] = buf;
  std::string joined;
  for (size_t i = 0; i < toks.size(); ++i) {
    if (i) joined += ' ';
    joined += toks[i];
  }
  text->replace(start, end - start, joined);
  return true;
}

}  // namespace

int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val) {
  if (lgbm_tpu_internal::IsTrainBooster(handle))
    return Fail("LGBM_BoosterSetLeafValue: training boosters are read-only "
                "through the C model surface (their native model is "
                "resynced from the engine); patch leaves on the Python "
                "Booster instead");
  Model* m = static_cast<Model*>(handle);
  if (m == nullptr) return -1;
  if (tree_idx < 0 || tree_idx >= static_cast<int>(m->trees.size()))
    return Fail("tree_idx " + std::to_string(tree_idx) +
                " out of range for " + std::to_string(m->trees.size()) +
                " trees");
  Tree& t = m->trees[tree_idx];
  if (leaf_idx < 0 || leaf_idx >= static_cast<int>(t.leaf_value.size()))
    return Fail("leaf_idx " + std::to_string(leaf_idx) +
                " out of range for " + std::to_string(t.leaf_value.size()) +
                " leaves");
  if (!PatchLeafValueInText(&m->text, tree_idx, leaf_idx, val))
    return Fail("could not locate tree " + std::to_string(tree_idx) +
                "'s leaf_value line in the stored model text");
  t.leaf_value[leaf_idx] = val;
  return 0;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                          const char* filename) {
  int64_t len = 0;
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  (void)num_iteration;  // full stored text; truncation is a Python-side task
  std::ofstream f(filename);
  if (!f) return Fail(std::string("cannot open for write: ") + filename);
  f << m->text;
  len = static_cast<int64_t>(m->text.size());
  return len >= 0 ? 0 : -1;
}

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int num_iteration,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str) {
  (void)num_iteration;
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  *out_len = static_cast<int64_t>(m->text.size()) + 1;
  if (buffer_len >= *out_len && out_str != nullptr) {
    std::memcpy(out_str, m->text.c_str(), m->text.size() + 1);
  }
  return 0;
}

int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (importance_type != C_API_FEATURE_IMPORTANCE_SPLIT &&
      importance_type != C_API_FEATURE_IMPORTANCE_GAIN)
    return Fail("unsupported importance_type " +
                std::to_string(importance_type));
  int nfeat = m->max_feature_idx + 1;
  std::fill(out_results, out_results + nfeat, 0.0);
  int iters = m->NumIterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int used_trees = iters * m->num_tree_per_iteration;
  for (int t = 0; t < used_trees; ++t) {
    const Tree& tr = m->trees[t];
    int ni = tr.num_leaves - 1;
    for (int n = 0; n < ni; ++n) {
      int f = tr.split_feature[n];
      if (f < 0 || f >= nfeat) continue;
      if (importance_type == C_API_FEATURE_IMPORTANCE_GAIN) {
        // gbdt.cpp FeatureImportance: negative recorded gains clamp to 0
        double g = n < static_cast<int>(tr.split_gain.size())
                       ? tr.split_gain[n] : 0.0;
        out_results[f] += std::max(g, 0.0);
      } else {
        out_results[f] += 1.0;
      }
    }
  }
  return 0;
}

namespace {

void JsonEscape(const std::string& s, std::string* out) {
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out->push_back('\\');
      out->push_back(c);
    } else if (static_cast<unsigned char>(c) < 0x20) {
      char buf[8];
      std::snprintf(buf, sizeof(buf), "\\u%04x", c);
      *out += buf;
    } else {
      out->push_back(c);
    }
  }
}

void JsonNum(double v, std::string* out) {
  if (std::isnan(v)) { *out += "null"; return; }
  char buf[40];
  std::snprintf(buf, sizeof(buf), "%.17g", v);
  *out += buf;
}

// recursive node dump mirroring the Python binding's Tree._node_to_json
// (models/tree.py) so both dumps share one schema
void DumpNode(const Tree& t, int index, std::string* out) {
  if (index < 0) {
    int leaf = ~index;
    *out += "{\"leaf_index\":" + std::to_string(leaf) + ",\"leaf_value\":";
    JsonNum(t.leaf_value[leaf], out);
    int64_t cnt = leaf < static_cast<int>(t.leaf_count.size())
                      ? t.leaf_count[leaf] : 0;
    *out += ",\"leaf_count\":" + std::to_string(cnt) + "}";
    return;
  }
  int dt = t.decision_type[index];
  bool is_cat = (dt & kCategoricalMask) != 0;
  static const char* kMissing[] = {"None", "Zero", "NaN", "NaN"};
  *out += "{\"split_index\":" + std::to_string(index);
  *out += ",\"split_feature\":" + std::to_string(t.split_feature[index]);
  *out += ",\"split_gain\":";
  JsonNum(index < static_cast<int>(t.split_gain.size())
              ? t.split_gain[index] : 0.0, out);
  *out += ",\"missing_type\":\"";
  *out += kMissing[(dt >> 2) & 3];
  *out += "\",\"default_left\":";
  *out += (dt & kDefaultLeftMask) ? "true" : "false";
  *out += ",\"internal_value\":";
  JsonNum(index < static_cast<int>(t.internal_value.size())
              ? t.internal_value[index] : 0.0, out);
  int64_t icnt = index < static_cast<int>(t.internal_count.size())
                     ? t.internal_count[index] : 0;
  *out += ",\"internal_count\":" + std::to_string(icnt);
  if (is_cat) {
    int ci = static_cast<int>(t.threshold[index]);
    *out += ",\"decision_type\":\"==\",\"threshold\":\"";
    bool first = true;
    if (ci + 1 < static_cast<int>(t.cat_boundaries.size())) {
      for (int64_t w = t.cat_boundaries[ci]; w < t.cat_boundaries[ci + 1];
           ++w) {
        for (int b = 0; b < 32; ++b) {
          if ((t.cat_threshold[w] >> b) & 1) {
            if (!first) *out += "||";
            first = false;
            *out += std::to_string((w - t.cat_boundaries[ci]) * 32 + b);
          }
        }
      }
    }
    *out += "\"";
  } else {
    *out += ",\"decision_type\":\"<=\",\"threshold\":";
    JsonNum(t.threshold[index], out);
  }
  *out += ",\"left_child\":";
  DumpNode(t, t.left_child[index], out);
  *out += ",\"right_child\":";
  DumpNode(t, t.right_child[index], out);
  *out += "}";
}

}  // namespace

int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  (void)feature_importance_type;  // importances ride the dedicated entry
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  int total_iter = m->NumIterations();
  int start = std::max(0, std::min(start_iteration, total_iter));
  int end = total_iter;
  if (num_iteration > 0) end = std::min(start + num_iteration, total_iter);
  int k = m->num_tree_per_iteration;

  std::string js = "{\"name\":\"tree\",\"version\":\"v2\"";
  js += ",\"num_class\":" + std::to_string(m->num_class);
  js += ",\"num_tree_per_iteration\":" + std::to_string(k);
  js += ",\"label_index\":0";
  js += ",\"max_feature_idx\":" + std::to_string(m->max_feature_idx);
  js += ",\"objective\":\"";
  JsonEscape(m->objective, &js);
  js += "\",\"average_output\":";
  js += m->average_output ? "true" : "false";
  js += ",\"feature_names\":[";
  for (int f = 0; f <= m->max_feature_idx; ++f) {
    if (f) js += ",";
    js += "\"";
    if (f < static_cast<int>(m->feature_names.size()))
      JsonEscape(m->feature_names[f], &js);
    else
      js += "Column_" + std::to_string(f);
    js += "\"";
  }
  js += "],\"tree_info\":[";
  for (int t = start * k; t < end * k; ++t) {
    if (t > start * k) js += ",";
    const Tree& tr = m->trees[t];
    js += "{\"tree_index\":" + std::to_string(t - start * k);
    js += ",\"num_leaves\":" + std::to_string(tr.num_leaves);
    js += ",\"num_cat\":" + std::to_string(tr.num_cat);
    js += ",\"shrinkage\":";
    JsonNum(tr.shrinkage, &js);
    js += ",\"tree_structure\":";
    if (tr.num_leaves <= 1) {
      js += "{\"leaf_value\":";
      JsonNum(tr.leaf_value.empty() ? 0.0 : tr.leaf_value[0], &js);
      js += "}";
    } else {
      DumpNode(tr, 0, &js);
    }
    js += "}";
  }
  js += "]}";

  *out_len = static_cast<int64_t>(js.size()) + 1;
  if (buffer_len >= *out_len && out_str != nullptr) {
    std::memcpy(out_str, js.c_str(), js.size() + 1);
  }
  return 0;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  (void)parameter;
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  int nfeat = m->max_feature_idx + 1;
  if (ncol < nfeat)
    return Fail("input has " + std::to_string(ncol) + " columns, model needs " +
                std::to_string(nfeat));
  int k = m->num_tree_per_iteration;
  int iters = m->NumIterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int used_trees = iters * k;

  auto at = [&](int32_t r, int32_t c) -> double {
    int64_t idx = is_row_major ? static_cast<int64_t>(r) * ncol + c
                               : static_cast<int64_t>(c) * nrow + r;
    if (data_type == C_API_DTYPE_FLOAT32)
      return static_cast<const float*>(data)[idx];
    return static_cast<const double*>(data)[idx];
  };

  bool leaf = predict_type == C_API_PREDICT_LEAF_INDEX;
  if (!leaf && predict_type != C_API_PREDICT_NORMAL &&
      predict_type != C_API_PREDICT_RAW_SCORE)
    return Fail("unsupported predict_type " + std::to_string(predict_type));
  int64_t width = leaf ? used_trees : k;
  // rows are independent — the reference's Predictor parallelizes the same
  // way (predictor.hpp OpenMP pipeline)
#pragma omp parallel
  {
    std::vector<double> row(ncol);
#pragma omp for schedule(static)
    for (int32_t r = 0; r < nrow; ++r) {
      for (int32_t c = 0; c < ncol; ++c) row[c] = at(r, c);
      PredictRow(*m, row.data(), predict_type, iters, used_trees,
                 out_result + r * width);
    }
  }
  *out_len = static_cast<int64_t>(nrow) * width;
  return 0;
}

int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int num_iteration,
                               int64_t* out_len) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (num_row < 0) return Fail("num_row must be >= 0");
  int k = m->num_tree_per_iteration;
  int iters = m->NumIterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int64_t width;
  if (predict_type == C_API_PREDICT_LEAF_INDEX) {
    width = static_cast<int64_t>(iters) * k;
  } else if (predict_type == C_API_PREDICT_NORMAL ||
             predict_type == C_API_PREDICT_RAW_SCORE) {
    width = k;
  } else {
    return Fail("unsupported predict_type " + std::to_string(predict_type));
  }
  *out_len = static_cast<int64_t>(num_row) * width;
  return 0;
}

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const void* data, int data_type,
                                       int ncol, int is_row_major,
                                       int predict_type, int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  // one row is one row in either majorness
  (void)is_row_major;
  return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol, 1,
                                   predict_type, num_iteration, parameter,
                                   out_len, out_result);
}

int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int num_iteration, const char* parameter,
                               const char* result_filename) {
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (predict_type != C_API_PREDICT_NORMAL &&
      predict_type != C_API_PREDICT_RAW_SCORE &&
      predict_type != C_API_PREDICT_LEAF_INDEX)
    return Fail("unsupported predict_type " + std::to_string(predict_type));

  // label_column=<idx> from the parameter string (default 0, like the
  // Python CLI's predict task)
  int label_col = 0;
  if (parameter != nullptr) {
    const char* p = strstr(parameter, "label_column=");
    if (p != nullptr) label_col = atoi(p + strlen("label_column="));
  }

  // sniff separator + column count from the first non-blank lines (the
  // Python parser's detect_format: tab beats comma, tsv is the default)
  std::ifstream f(data_filename);
  if (!f) return Fail(std::string("cannot open data file: ") + data_filename);
  std::string line, first_body;
  bool saw_first = false, skipped_header = !data_has_header;
  char sep = '\t';
  while (std::getline(f, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    bool blank = line.find_first_not_of(" \t\r\n") == std::string::npos;
    if (blank) continue;
    if (!saw_first) {
      saw_first = true;
      if (line.find('\t') != std::string::npos) sep = '\t';
      else if (line.find(',') != std::string::npos) sep = ',';
    }
    if (!skipped_header) {  // this non-blank line IS the header
      skipped_header = true;
      continue;
    }
    first_body = line;
    break;
  }
  f.close();
  if (first_body.empty()) return Fail("data file is empty or unparseable");
  int n_cols = 1 + static_cast<int>(
      std::count(first_body.begin(), first_body.end(), sep));
  if (n_cols < 2) return Fail("data file needs at least 2 columns");
  if (label_col >= n_cols)
    return Fail("label_column " + std::to_string(label_col) +
                " out of range for " + std::to_string(n_cols) + " columns");

  long long nrow = LGBMT_CountRows(data_filename, data_has_header, sep);
  if (nrow < 0)
    return Fail(std::string("cannot read data file: ") + data_filename);
  if (nrow == 0) return Fail("data file has no rows");
  int n_parsed = n_cols - 1;
  std::vector<double> X(static_cast<size_t>(nrow) * n_parsed);
  std::vector<double> y(nrow);
  int rc = LGBMT_ParseDense(data_filename, sep, data_has_header, nrow,
                            n_cols, label_col, X.data(), y.data());
  if (rc == -4) return Fail("ragged rows in data file");
  if (rc == -5) return Fail("non-numeric token in data file");
  if (rc != 0)
    return Fail("data parse failed (rc " + std::to_string(rc) + ")");

  int nfeat = m->max_feature_idx + 1;
  int k = m->num_tree_per_iteration;
  int iters = m->NumIterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int used_trees = iters * k;
  bool leaf = predict_type == C_API_PREDICT_LEAF_INDEX;
  int64_t width = leaf ? used_trees : k;
  std::vector<double> out(static_cast<size_t>(nrow) * width);
  // rows parsed narrower than the model pad with NaN, wider truncate —
  // the Python loader's _fix_width semantics
#pragma omp parallel
  {
    std::vector<double> row(nfeat);
#pragma omp for schedule(static)
    for (long long r = 0; r < nrow; ++r) {
      const double* xrow = X.data() + r * n_parsed;
      int copy = std::min(n_parsed, nfeat);
      for (int c = 0; c < copy; ++c) row[c] = xrow[c];
      for (int c = copy; c < nfeat; ++c) row[c] = NAN;
      PredictRow(*m, row.data(), predict_type, iters, used_trees,
                 out.data() + r * width);
    }
  }

  // "%.18g" + tab-join + "\n": the exact format application.py's
  // predict task writes, so outputs compare byte-for-byte
  std::FILE* rf = std::fopen(result_filename, "w");
  if (rf == nullptr)
    return Fail(std::string("cannot open for write: ") + result_filename);
  char buf[64];
  for (long long r = 0; r < nrow; ++r) {
    for (int64_t j = 0; j < width; ++j) {
      std::snprintf(buf, sizeof(buf), "%.18g", out[r * width + j]);
      std::fputs(buf, rf);
      std::fputc(j + 1 < width ? '\t' : '\n', rf);
    }
  }
  std::fclose(rf);
  return 0;
}

// Reusable single-row predict state (reference
// LGBM_BoosterPredictForMatSingleRowFast): schema checks, iteration
// resolution and the row buffer are paid once in Init; each Fast call
// is one traversal.  One caller thread at a time per config (the row
// buffer is shared state — the reference has the same contract).
struct FastConfig {
  BoosterHandle handle;
  int predict_type;
  int data_type;
  int32_t ncol;
  int num_iteration;
  std::vector<double> row;
};

int LGBM_BoosterPredictForMatSingleRowFastInit(
    BoosterHandle handle, int predict_type, int data_type, int32_t ncol,
    const char* parameter, int num_iteration, FastConfigHandle* out_fast) {
  (void)parameter;
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (predict_type != C_API_PREDICT_NORMAL &&
      predict_type != C_API_PREDICT_RAW_SCORE &&
      predict_type != C_API_PREDICT_LEAF_INDEX)
    return Fail("unsupported predict_type " + std::to_string(predict_type));
  if (data_type != C_API_DTYPE_FLOAT32 && data_type != C_API_DTYPE_FLOAT64)
    return Fail("data_type must be C_API_DTYPE_FLOAT32/FLOAT64, got " +
                std::to_string(data_type));
  int nfeat = m->max_feature_idx + 1;
  if (ncol < nfeat)
    return Fail("input has " + std::to_string(ncol) + " columns, model needs " +
                std::to_string(nfeat));
  auto* fc = new FastConfig();
  fc->handle = handle;
  fc->predict_type = predict_type;
  fc->data_type = data_type;
  fc->ncol = ncol;
  fc->num_iteration = num_iteration;
  fc->row.resize(ncol);
  *out_fast = fc;
  return 0;
}

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fast_config,
                                           const void* data,
                                           int64_t* out_len,
                                           double* out_result) {
  auto* fc = static_cast<FastConfig*>(fast_config);
  if (fc == nullptr) return Fail("fast_config is null");
  // resolve per call: for a training booster this takes the shared model
  // lock, so concurrent UpdateOneIter resyncs stay safe
  ModelRef ref(fc->handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (fc->data_type == C_API_DTYPE_FLOAT32) {
    const float* src = static_cast<const float*>(data);
    for (int32_t c = 0; c < fc->ncol; ++c) fc->row[c] = src[c];
  } else {
    std::memcpy(fc->row.data(), data, sizeof(double) * fc->ncol);
  }
  int k = m->num_tree_per_iteration;
  int iters = m->NumIterations();
  if (fc->num_iteration > 0 && fc->num_iteration < iters)
    iters = fc->num_iteration;
  int used_trees = iters * k;
  PredictRow(*m, fc->row.data(), fc->predict_type, iters, used_trees,
             out_result);
  *out_len = fc->predict_type == C_API_PREDICT_LEAF_INDEX ? used_trees : k;
  return 0;
}

int LGBM_FastConfigFree(FastConfigHandle fast_config) {
  delete static_cast<FastConfig*>(fast_config);
  return 0;
}

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem, int64_t num_col,
                              int predict_type, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  (void)nelem;
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (indptr_type != C_API_DTYPE_INT32 && indptr_type != C_API_DTYPE_INT64)
    return Fail("indptr_type must be C_API_DTYPE_INT32/INT64, got " +
                std::to_string(indptr_type));
  if (data_type != C_API_DTYPE_FLOAT32 && data_type != C_API_DTYPE_FLOAT64)
    return Fail("data_type must be C_API_DTYPE_FLOAT32/FLOAT64, got " +
                std::to_string(data_type));
  int nfeat = m->max_feature_idx + 1;
  if (num_col < nfeat)
    return Fail("CSR has " + std::to_string(num_col) +
                " columns, model needs " + std::to_string(nfeat));
  int k = m->num_tree_per_iteration;
  int iters = m->NumIterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int used_trees = iters * k;
  int64_t nrow = nindptr - 1;

  auto row_range = [&](int64_t r, int64_t* b, int64_t* e) {
    if (indptr_type == C_API_DTYPE_INT32) {
      *b = static_cast<const int32_t*>(indptr)[r];
      *e = static_cast<const int32_t*>(indptr)[r + 1];
    } else {
      *b = static_cast<const int64_t*>(indptr)[r];
      *e = static_cast<const int64_t*>(indptr)[r + 1];
    }
  };
  auto val = [&](int64_t i) -> double {
    if (data_type == C_API_DTYPE_FLOAT32)
      return static_cast<const float*>(data)[i];
    return static_cast<const double*>(data)[i];
  };

  bool leaf = predict_type == C_API_PREDICT_LEAF_INDEX;
  if (!leaf && predict_type != C_API_PREDICT_NORMAL &&
      predict_type != C_API_PREDICT_RAW_SCORE)
    return Fail("unsupported predict_type " + std::to_string(predict_type));

  int64_t width = leaf ? used_trees : k;
  // each thread scatters into its own dense row buffer; cap the team so
  // the combined buffers stay within ~256 MB on very wide sparse inputs
  int team = 1;
#ifdef _OPENMP
  team = static_cast<int>(std::max<int64_t>(
      1, std::min<int64_t>(omp_get_max_threads(),
                           (256LL << 20) / (num_col * 8 + 1))));
#endif
#pragma omp parallel num_threads(team)
  {
    std::vector<double> prow(num_col, 0.0);
#pragma omp for schedule(static)
    for (int64_t r = 0; r < nrow; ++r) {
      int64_t b, e;
      row_range(r, &b, &e);
      for (int64_t i = b; i < e; ++i) prow[indices[i]] = val(i);
      PredictRow(*m, prow.data(), predict_type, iters, used_trees,
                 out_result + r * width);
      for (int64_t i = b; i < e; ++i) prow[indices[i]] = 0.0;  // reset
    }
  }
  *out_len = nrow * width;
  return 0;
}

int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  (void)parameter;
  (void)nelem;
  ModelRef ref(handle);
  Model* m = ref.m;
  if (m == nullptr) return -1;
  if (col_ptr_type != C_API_DTYPE_INT32 && col_ptr_type != C_API_DTYPE_INT64)
    return Fail("col_ptr_type must be C_API_DTYPE_INT32/INT64, got " +
                std::to_string(col_ptr_type));
  if (data_type != C_API_DTYPE_FLOAT32 && data_type != C_API_DTYPE_FLOAT64)
    return Fail("data_type must be C_API_DTYPE_FLOAT32/FLOAT64, got " +
                std::to_string(data_type));
  int64_t ncol = ncol_ptr - 1;
  int nfeat = m->max_feature_idx + 1;
  if (ncol < nfeat)
    return Fail("CSC has " + std::to_string(ncol) +
                " columns, model needs " + std::to_string(nfeat));
  bool leaf = predict_type == C_API_PREDICT_LEAF_INDEX;
  if (!leaf && predict_type != C_API_PREDICT_NORMAL &&
      predict_type != C_API_PREDICT_RAW_SCORE)
    return Fail("unsupported predict_type " + std::to_string(predict_type));
  int k = m->num_tree_per_iteration;
  int iters = m->NumIterations();
  if (num_iteration > 0 && num_iteration < iters) iters = num_iteration;
  int used_trees = iters * k;
  int64_t width = leaf ? used_trees : k;

  auto col_range = [&](int64_t c, int64_t* b, int64_t* e) {
    if (col_ptr_type == C_API_DTYPE_INT32) {
      *b = static_cast<const int32_t*>(col_ptr)[c];
      *e = static_cast<const int32_t*>(col_ptr)[c + 1];
    } else {
      *b = static_cast<const int64_t*>(col_ptr)[c];
      *e = static_cast<const int64_t*>(col_ptr)[c + 1];
    }
  };
  auto val = [&](int64_t i) -> double {
    if (data_type == C_API_DTYPE_FLOAT32)
      return static_cast<const float*>(data)[i];
    return static_cast<const double*>(data)[i];
  };

  // one dense row-major scatter of the whole matrix: CSC carries whole
  // columns, so a per-row buffer cannot stream it the way CSR does
  std::vector<double> dense(static_cast<size_t>(num_row) * ncol, 0.0);
  for (int64_t c = 0; c < ncol; ++c) {
    int64_t b, e;
    col_range(c, &b, &e);
    for (int64_t i = b; i < e; ++i) {
      int64_t r = indices[i];
      if (r < 0 || r >= num_row)
        return Fail("CSC row index " + std::to_string(r) +
                    " out of range for num_row=" + std::to_string(num_row));
      dense[static_cast<size_t>(r) * ncol + c] = val(i);
    }
  }
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < num_row; ++r) {
    PredictRow(*m, dense.data() + static_cast<size_t>(r) * ncol,
               predict_type, iters, used_trees, out_result + r * width);
  }
  *out_len = num_row * width;
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRow(BoosterHandle handle,
                                       const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int predict_type,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result) {
  if (nindptr != 2)
    return Fail("PredictForCSRSingleRow takes exactly one row "
                "(nindptr must be 2, got " + std::to_string(nindptr) + ")");
  // the batch entry point's per-row inner loop IS the single-row path
  // (dense scatter + PredictRow); nothing cheaper exists to delegate to
  return LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices,
                                   data, data_type, nindptr, nelem, num_col,
                                   predict_type, num_iteration, parameter,
                                   out_len, out_result);
}

}  // extern "C"
