/* Compiled reference client for the binary serving wire protocol
 * (ISSUE 16): proves the data plane from OUTSIDE Python at production
 * rates, with no dependency on capi.py or any Python tooling.
 *
 * Two modes:
 *
 *   wire_client tcp HOST PORT --probes F32FILE --ncols N [options]
 *   wire_client uds SOCKPATH  --probes F32FILE --ncols N [options]
 *       Closed-loop socket load: --conns threads each own one
 *       connection and send one LGBM_WIRE request frame per probe row
 *       batch, reading the response frame back (CRC-verified both
 *       ways).  Rejection frames count separately and their
 *       retry_after_s hint is honored (--no-backoff hammers through
 *       rejections instead — the offered-load overload phase).  With
 *       --expect FILE (float32, probe-rows x n_out) and --expect-gen G
 *       every response whose generation == G is byte-compared against
 *       the expected values.
 *
 *   wire_client fastconfig LIBPATH MODELFILE --probes F32FILE --ncols N
 *       In-process single-row ABI: dlopen lib_lightgbm_tpu.so, FastInit
 *       once, then drive LGBM_BoosterPredictForMatSingleRowFast in a
 *       closed loop — the compiled-caller contract of the C API.
 *
 *   wire_client shm SOCKPATH --probes F32FILE --ncols N [options]
 *       Shared-memory ring transport (ISSUE 20): handshake over the
 *       UDS plane (MSG_SHM_SETUP + SCM_RIGHTS fd pass), then a
 *       pipelined request loop that writes frames straight into the
 *       mapped request ring and reads responses off the response ring
 *       with ZERO syscalls in the spin-hot steady state.  Same frame
 *       format, CRC checks, and --expect byte-verification as the
 *       socket modes; extra knobs --pipeline D (frames in flight),
 *       --spin S (doorbell spin budget, seconds), --warmup W (seconds
 *       excluded from the syscall-window counters), --req-cap /
 *       --resp-cap (ring bytes, powers of two).
 *
 * Emits one JSON line on stdout (exp/bench_wire.py parses it).
 * Plain C99 + GNU syscall numbers for memfd_create (shm_open
 * fallback); crc32 is computed locally (zlib polynomial) so the
 * binary links against nothing beyond pthread/dl/m/rt.
 */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>
#include <dlfcn.h>
#include <netdb.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/un.h>
#include <netinet/in.h>
#include <netinet/tcp.h>

#include "lightgbm_tpu_c_api.h"

#define MAX_PAYLOAD (1 << 26)
#define MAX_LAT 2000000

/* ---------------------------------------------------------------- crc32 */
static uint32_t crc_table[256];

static void crc_init(void) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
}

static uint32_t crc32_buf(const uint8_t *p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

/* ------------------------------------------------------------- plumbing */
static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static int read_full(int fd, void *buf, size_t n) {
  uint8_t *p = (uint8_t *)buf;
  size_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, p + got, n - got);
    if (r <= 0) return -1;
    got += (size_t)r;
  }
  return 0;
}

static int write_full(int fd, const void *buf, size_t n) {
  const uint8_t *p = (const uint8_t *)buf;
  size_t put = 0;
  while (put < n) {
    ssize_t w = write(fd, p + put, n - put);
    if (w <= 0) return -1;
    put += (size_t)w;
  }
  return 0;
}

static int connect_tcp(const char *host, int port) {
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  struct addrinfo hints, *res = NULL;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host, portstr, &hints, &res) != 0) return -1;
  int fd = -1;
  for (struct addrinfo *ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  }
  return fd;
}

static int connect_uds(const char *path) {
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_un sa;
  memset(&sa, 0, sizeof sa);
  sa.sun_family = AF_UNIX;
  strncpy(sa.sun_path, path, sizeof sa.sun_path - 1);
  if (connect(fd, (struct sockaddr *)&sa, sizeof sa) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

/* --------------------------------------------------------- socket bench */
typedef struct {
  int is_uds;
  const char *host;
  const char *path;
  int port;
  const char *model_id;
  const float *probes;   /* [n_probes * ncols] */
  long n_probes;
  int ncols;
  int rows;              /* rows per request frame */
  const float *expect;   /* [n_probes * n_out] or NULL */
  int n_out;
  long expect_gen;       /* only verify responses from this generation */
  int no_backoff;        /* overload mode: ignore retry_after_s hints */
  volatile int *stop;
  /* outputs */
  long sent, completed, rejected, errors, checked, mismatch;
  double *lat;           /* seconds, up to MAX_LAT/conns each */
  long lat_cap, lat_n;
} worker_t;

static void put_header(uint8_t *h, uint8_t msg_type, const char *model_id,
                       uint32_t n_rows, uint32_t n_cols,
                       const uint8_t *payload, uint32_t payload_len) {
  LGBMWireFrameHeader *hdr = (LGBMWireFrameHeader *)h;
  memcpy(hdr->magic, LGBM_WIRE_MAGIC, 4);
  hdr->version = LGBM_WIRE_VERSION;
  hdr->msg_type = msg_type;
  hdr->dtype = LGBM_WIRE_DTYPE_F32;
  hdr->flags = 0;
  memset(hdr->model_id, 0, sizeof hdr->model_id);
  size_t id_len = strlen(model_id);
  if (id_len > sizeof hdr->model_id) id_len = sizeof hdr->model_id;
  memcpy(hdr->model_id, model_id, id_len); /* NUL-padded, not a C string */
  hdr->n_rows = n_rows;
  hdr->n_cols = n_cols;
  hdr->payload_len = payload_len;
  hdr->crc32 = crc32_buf(payload, payload_len);
}

static void *worker(void *arg) {
  worker_t *w = (worker_t *)arg;
  int fd = w->is_uds ? connect_uds(w->path) : connect_tcp(w->host, w->port);
  if (fd < 0) {
    w->errors++;
    return NULL;
  }
  uint32_t req_payload = (uint32_t)(w->rows * w->ncols) * 4u;
  uint8_t *frame = (uint8_t *)malloc(LGBM_WIRE_HEADER_SIZE + req_payload);
  uint8_t *resp = (uint8_t *)malloc(MAX_PAYLOAD);
  long probe = 0;
  while (!*w->stop) {
    /* gather `rows` consecutive probe rows (wrapping) into the frame */
    float *dst = (float *)(frame + LGBM_WIRE_HEADER_SIZE);
    for (int r = 0; r < w->rows; r++) {
      long idx = (probe + r) % w->n_probes;
      memcpy(dst + (size_t)r * w->ncols, w->probes + idx * w->ncols,
             (size_t)w->ncols * 4);
    }
    put_header(frame, LGBM_WIRE_MSG_REQUEST, w->model_id,
               (uint32_t)w->rows, (uint32_t)w->ncols,
               frame + LGBM_WIRE_HEADER_SIZE, req_payload);
    double t0 = now_s();
    if (write_full(fd, frame, LGBM_WIRE_HEADER_SIZE + req_payload) != 0) {
      w->errors++;
      break;
    }
    w->sent++;
    LGBMWireFrameHeader rh;
    if (read_full(fd, &rh, sizeof rh) != 0) {
      w->errors++;
      break;
    }
    if (memcmp(rh.magic, LGBM_WIRE_MAGIC, 4) != 0 ||
        rh.version != LGBM_WIRE_VERSION || rh.payload_len > MAX_PAYLOAD) {
      w->errors++;
      break;
    }
    if (read_full(fd, resp, rh.payload_len) != 0) {
      w->errors++;
      break;
    }
    if (crc32_buf(resp, rh.payload_len) != rh.crc32) {
      w->errors++;
      break;
    }
    double dt = now_s() - t0;
    if (rh.msg_type == LGBM_WIRE_MSG_RESPONSE) {
      w->completed++;
      if (w->lat_n < w->lat_cap) w->lat[w->lat_n++] = dt;
      if (w->expect && rh.n_rows == (uint32_t)w->rows &&
          rh.n_cols == (uint32_t)w->n_out) {
        /* resp meta block: generation is the leading int64 */
        int64_t gen;
        memcpy(&gen, resp, 8);
        if (gen == (int64_t)w->expect_gen) {
          const float *vals = (const float *)(resp + 32);
          for (int r = 0; r < w->rows; r++) {
            long idx = (probe + r) % w->n_probes;
            w->checked++;
            if (memcmp(vals + (size_t)r * w->n_out,
                       w->expect + idx * w->n_out,
                       (size_t)w->n_out * 4) != 0)
              w->mismatch++;
          }
        }
      }
    } else if (rh.msg_type == LGBM_WIRE_MSG_REJECT) {
      w->rejected++;
      float retry_after = 0.0f;
      uint8_t retryable = 0;
      if (rh.payload_len >= 8) {
        memcpy(&retry_after, resp, 4);
        retryable = resp[4];
      }
      if (!retryable) break;
      if (w->no_backoff) continue;  /* offered-load phase: hammer */
      if (retry_after > 0.0f) {
        struct timespec ts = {(time_t)retry_after,
                              (long)((retry_after - (float)(time_t)retry_after)
                                     * 1e9f)};
        nanosleep(&ts, NULL);
      }
    } else {
      w->errors++;
      break;
    }
    probe = (probe + w->rows) % w->n_probes;
  }
  free(frame);
  free(resp);
  close(fd);
  return NULL;
}

static int cmp_double(const void *a, const void *b) {
  double x = *(const double *)a, y = *(const double *)b;
  return (x > y) - (x < y);
}

static float *load_f32(const char *path, long *out_n) {
  FILE *f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  long bytes = ftell(f);
  fseek(f, 0, SEEK_SET);
  float *buf = (float *)malloc((size_t)bytes);
  if (fread(buf, 1, (size_t)bytes, f) != (size_t)bytes) {
    fclose(f);
    free(buf);
    return NULL;
  }
  fclose(f);
  *out_n = bytes / 4;
  return buf;
}

static int run_socket(int argc, char **argv, int is_uds) {
  const char *host = NULL, *path = NULL;
  int port = 0, arg = 2;
  if (is_uds) {
    path = argv[arg++];
  } else {
    host = argv[arg++];
    port = atoi(argv[arg++]);
  }
  const char *probes_path = NULL, *expect_path = NULL;
  const char *model_id = "default";
  int conns = 4, ncols = 0, rows = 1, n_out = 1, no_backoff = 0;
  long expect_gen = -1;
  double secs = 5.0;
  for (; arg < argc; arg++) {
    if (!strcmp(argv[arg], "--probes")) probes_path = argv[++arg];
    else if (!strcmp(argv[arg], "--expect")) expect_path = argv[++arg];
    else if (!strcmp(argv[arg], "--expect-gen")) expect_gen = atol(argv[++arg]);
    else if (!strcmp(argv[arg], "--ncols")) ncols = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--n-out")) n_out = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--rows")) rows = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--conns")) conns = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--secs")) secs = atof(argv[++arg]);
    else if (!strcmp(argv[arg], "--model")) model_id = argv[++arg];
    else if (!strcmp(argv[arg], "--no-backoff")) no_backoff = 1;
    else { fprintf(stderr, "unknown arg %s\n", argv[arg]); return 2; }
  }
  if (!probes_path || ncols <= 0) {
    fprintf(stderr, "--probes FILE and --ncols N are required\n");
    return 2;
  }
  long n_vals = 0;
  float *probes = load_f32(probes_path, &n_vals);
  if (!probes || n_vals % ncols) {
    fprintf(stderr, "bad probes file %s\n", probes_path);
    return 2;
  }
  long n_probes = n_vals / ncols;
  float *expect = NULL;
  if (expect_path) {
    long en = 0;
    expect = load_f32(expect_path, &en);
    if (!expect || en != n_probes * n_out) {
      fprintf(stderr, "expect file size mismatch (%ld vs %ld)\n", en,
              n_probes * n_out);
      return 2;
    }
  }
  volatile int stop = 0;
  worker_t *ws = (worker_t *)calloc((size_t)conns, sizeof(worker_t));
  pthread_t *tids = (pthread_t *)calloc((size_t)conns, sizeof(pthread_t));
  long cap = MAX_LAT / (conns > 0 ? conns : 1);
  for (int i = 0; i < conns; i++) {
    ws[i] = (worker_t){.is_uds = is_uds, .host = host, .path = path,
                       .port = port, .model_id = model_id,
                       .probes = probes, .n_probes = n_probes,
                       .ncols = ncols, .rows = rows, .expect = expect,
                       .n_out = n_out, .expect_gen = expect_gen,
                       .no_backoff = no_backoff, .stop = &stop,
                       .lat = (double *)malloc((size_t)cap * sizeof(double)),
                       .lat_cap = cap};
    pthread_create(&tids[i], NULL, worker, &ws[i]);
  }
  double t0 = now_s();
  struct timespec tick = {0, 10000000L};
  while (now_s() - t0 < secs) nanosleep(&tick, NULL);
  stop = 1;
  for (int i = 0; i < conns; i++) pthread_join(tids[i], NULL);
  double elapsed = now_s() - t0;

  long sent = 0, completed = 0, rejected = 0, errors = 0, checked = 0,
       mismatch = 0, lat_n = 0;
  for (int i = 0; i < conns; i++) {
    sent += ws[i].sent;
    completed += ws[i].completed;
    rejected += ws[i].rejected;
    errors += ws[i].errors;
    checked += ws[i].checked;
    mismatch += ws[i].mismatch;
    lat_n += ws[i].lat_n;
  }
  double *lat = (double *)malloc((size_t)(lat_n > 0 ? lat_n : 1)
                                 * sizeof(double));
  long k = 0;
  for (int i = 0; i < conns; i++)
    for (long j = 0; j < ws[i].lat_n; j++) lat[k++] = ws[i].lat[j];
  qsort(lat, (size_t)lat_n, sizeof(double), cmp_double);
  double p50 = lat_n ? lat[(long)(0.50 * (double)(lat_n - 1))] : 0.0;
  double p99 = lat_n ? lat[(long)(0.99 * (double)(lat_n - 1))] : 0.0;
  printf("{\"mode\":\"%s\",\"conns\":%d,\"rows\":%d,\"elapsed_s\":%.3f,"
         "\"sent\":%ld,\"completed\":%ld,\"rejected\":%ld,\"errors\":%ld,"
         "\"verify_checked\":%ld,\"verify_mismatch\":%ld,"
         "\"req_per_sec\":%.1f,\"rows_per_sec\":%.1f,"
         "\"p50_ms\":%.4f,\"p99_ms\":%.4f}\n",
         is_uds ? "uds" : "tcp", conns, rows, elapsed, sent, completed,
         rejected, errors, checked, mismatch,
         (double)completed / elapsed, (double)(completed * rows) / elapsed,
         p50 * 1e3, p99 * 1e3);
  return (errors > 0 || completed == 0 || mismatch > 0) ? 1 : 0;
}

/* ------------------------------------------------------------- shm mode */
/* SPSC ring over a memfd segment shared with the server; layout and
 * counter protocol mirror runtime/shm_ring.py._Ring exactly (pinned by
 * the LGBMWireRingHeader ABI block in lightgbm_tpu_c_api.h).  Counters
 * are free-running u64s, position = counter & (capacity-1); a frame
 * that would straddle the segment boundary is preceded by the 4-byte
 * LGBM_WIRE_RING_WRAP marker (implicit skip when < 4 bytes remain). */

typedef struct {
  uint8_t *data;
  uint64_t cap, mask;
  volatile uint64_t *tail, *head;
  volatile uint32_t *waiter;
} ring_t;

static void ring_init(ring_t *r, uint8_t *seg, uint32_t ctrl, uint32_t off,
                      uint32_t cap) {
  r->data = seg + off;
  r->cap = cap;
  r->mask = (uint64_t)cap - 1;
  r->tail = (volatile uint64_t *)(seg + ctrl);
  r->head = (volatile uint64_t *)(seg + ctrl + 64);
  r->waiter = (volatile uint32_t *)(seg + ctrl + 128);
}

/* producer: reserve `need` contiguous bytes; fills out_tail/out_pad
 * and returns the frame's byte offset inside the ring data, or -1 when
 * the ring is full (caller drains responses and retries). */
static int64_t ring_reserve(ring_t *r, uint64_t need, uint64_t *out_tail,
                            uint64_t *out_pad) {
  uint64_t tail = __atomic_load_n(r->tail, __ATOMIC_SEQ_CST);
  uint64_t head = __atomic_load_n(r->head, __ATOMIC_SEQ_CST);
  uint64_t pos = tail & r->mask;
  uint64_t room = r->cap - pos;
  uint64_t pad = (room < need) ? room : 0;
  if (need + pad > r->cap - (tail - head)) return -1;
  *out_tail = tail;
  *out_pad = pad;
  return (int64_t)((tail + pad) & r->mask);
}

static void ring_publish(ring_t *r, uint64_t tail, uint64_t pad,
                         uint64_t need) {
  if (pad >= 4) {
    uint32_t wrap = LGBM_WIRE_RING_WRAP;
    memcpy(r->data + (tail & r->mask), &wrap, 4);
  }
  __atomic_store_n(r->tail, tail + pad + need, __ATOMIC_SEQ_CST);
}

/* producer-side doorbell: wake the peer only if it advertised that it
 * is sleeping — zero syscalls while both sides stay in their spin. */
static void ring_bell(ring_t *r, int efd, long *db_rings) {
  if (__atomic_load_n(r->waiter, __ATOMIC_SEQ_CST)) {
    __atomic_store_n(r->waiter, 0u, __ATOMIC_SEQ_CST);
    uint64_t one = 1;
    (*db_rings)++;
    if (write(efd, &one, 8) < 0 && errno != EAGAIN)
      perror("doorbell write");
  }
}

/* consumer-side wait: bounded spin, then advertise via the waiter flag
 * and poll the eventfd (plus the control socket, whose readability
 * means the server went away).  Returns 0 when data is available, -1
 * on peer death / poll error. */
static int ring_wait(ring_t *r, int efd, int ctrl_sock, double spin_s,
                     long *db_waits, long *db_drains) {
  double spin_until = now_s() + spin_s;
  int iters = 0;
  for (;;) {
    if (__atomic_load_n(r->tail, __ATOMIC_SEQ_CST) !=
        __atomic_load_n(r->head, __ATOMIC_SEQ_CST))
      return 0;
    if (++iters >= 256) {
      iters = 0;
      if (now_s() >= spin_until) break;
    }
  }
  for (;;) {
    __atomic_store_n(r->waiter, 1u, __ATOMIC_SEQ_CST);
    if (__atomic_load_n(r->tail, __ATOMIC_SEQ_CST) !=
        __atomic_load_n(r->head, __ATOMIC_SEQ_CST)) {
      __atomic_store_n(r->waiter, 0u, __ATOMIC_SEQ_CST);
      return 0;
    }
    struct pollfd pfd[2] = {{efd, POLLIN, 0}, {ctrl_sock, POLLIN, 0}};
    (*db_waits)++;
    int n = poll(pfd, 2, 250);
    __atomic_store_n(r->waiter, 0u, __ATOMIC_SEQ_CST);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pfd[1].revents) return -1; /* control socket: server closed */
    if (pfd[0].revents & POLLIN) {
      uint64_t v;
      (*db_drains)++;
      if (read(efd, &v, 8) < 0 && errno != EAGAIN) return -1;
    }
  }
}

#ifndef MFD_CLOEXEC
#define MFD_CLOEXEC 0x0001U
#endif

static int make_seg_fd(uint64_t size) {
  int fd = -1;
#ifdef SYS_memfd_create
  fd = (int)syscall(SYS_memfd_create, "lgbm-shm-ring", (unsigned)MFD_CLOEXEC);
#endif
  if (fd < 0) { /* pre-memfd kernels: anonymous POSIX shm */
    char name[64];
    snprintf(name, sizeof name, "/lgbm-shm-ring-%d", (int)getpid());
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd >= 0) shm_unlink(name);
  }
  if (fd < 0) return -1;
  if (ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

static int send_three_fds(int sock, int seg_fd, int efd_req, int efd_resp) {
  char data = 'F';
  struct iovec iov = {&data, 1};
  union {
    struct cmsghdr hdr;
    char buf[CMSG_SPACE(3 * sizeof(int))];
  } u;
  memset(&u, 0, sizeof u);
  struct msghdr msg;
  memset(&msg, 0, sizeof msg);
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = u.buf;
  msg.msg_controllen = sizeof u.buf;
  struct cmsghdr *c = CMSG_FIRSTHDR(&msg);
  c->cmsg_level = SOL_SOCKET;
  c->cmsg_type = SCM_RIGHTS;
  c->cmsg_len = CMSG_LEN(3 * sizeof(int));
  int fds[3] = {seg_fd, efd_req, efd_resp};
  memcpy(CMSG_DATA(c), fds, sizeof fds);
  return (sendmsg(sock, &msg, 0) == 1) ? 0 : -1;
}

static int expect_shm_ok(int fd) {
  LGBMWireFrameHeader h;
  if (read_full(fd, &h, sizeof h) != 0) return -1;
  if (memcmp(h.magic, LGBM_WIRE_MAGIC, 4) != 0 ||
      h.payload_len > MAX_PAYLOAD)
    return -1;
  uint8_t *pl = (uint8_t *)malloc(h.payload_len ? h.payload_len : 1);
  int rc = read_full(fd, pl, h.payload_len);
  if (rc == 0 && h.msg_type != LGBM_WIRE_MSG_SHM_OK) {
    fprintf(stderr, "shm handshake refused (msg_type %u)\n",
            (unsigned)h.msg_type);
    rc = -1;
  }
  free(pl);
  return rc;
}

static int run_shm(int argc, char **argv) {
  const char *path = argv[2];
  const char *probes_path = NULL, *expect_path = NULL;
  const char *model_id = "default";
  int ncols = 0, rows = 1, n_out = 1, pipeline = 16;
  long expect_gen = -1;
  double secs = 5.0, spin_s = 0.002, warmup = 1.0;
  uint64_t req_cap = LGBM_WIRE_RING_DEFAULT_CAP;
  uint64_t resp_cap = LGBM_WIRE_RING_DEFAULT_CAP;
  for (int arg = 3; arg < argc; arg++) {
    if (!strcmp(argv[arg], "--probes")) probes_path = argv[++arg];
    else if (!strcmp(argv[arg], "--expect")) expect_path = argv[++arg];
    else if (!strcmp(argv[arg], "--expect-gen")) expect_gen = atol(argv[++arg]);
    else if (!strcmp(argv[arg], "--ncols")) ncols = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--n-out")) n_out = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--rows")) rows = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--secs")) secs = atof(argv[++arg]);
    else if (!strcmp(argv[arg], "--model")) model_id = argv[++arg];
    else if (!strcmp(argv[arg], "--pipeline")) pipeline = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--spin")) spin_s = atof(argv[++arg]);
    else if (!strcmp(argv[arg], "--warmup")) warmup = atof(argv[++arg]);
    else if (!strcmp(argv[arg], "--req-cap")) req_cap = strtoull(argv[++arg], NULL, 0);
    else if (!strcmp(argv[arg], "--resp-cap")) resp_cap = strtoull(argv[++arg], NULL, 0);
    else { fprintf(stderr, "unknown arg %s\n", argv[arg]); return 2; }
  }
  if (!probes_path || ncols <= 0) {
    fprintf(stderr, "--probes FILE and --ncols N are required\n");
    return 2;
  }
  if (pipeline < 1) pipeline = 1;
  long n_vals = 0;
  float *probes = load_f32(probes_path, &n_vals);
  if (!probes || n_vals % ncols) {
    fprintf(stderr, "bad probes file %s\n", probes_path);
    return 2;
  }
  long n_probes = n_vals / ncols;
  float *expect = NULL;
  if (expect_path) {
    long en = 0;
    expect = load_f32(expect_path, &en);
    if (!expect || en != n_probes * n_out) {
      fprintf(stderr, "expect file size mismatch (%ld vs %ld)\n", en,
              n_probes * n_out);
      return 2;
    }
  }

  /* ---- handshake: setup frame, ack, fd pass, ack ---- */
  int sock = connect_uds(path);
  if (sock < 0) {
    fprintf(stderr, "connect %s: %s\n", path, strerror(errno));
    return 1;
  }
  LGBMWireRingHeader cfg;
  memset(&cfg, 0, sizeof cfg);
  memcpy(cfg.magic, LGBM_WIRE_RING_MAGIC, 4);
  cfg.version = LGBM_WIRE_RING_VERSION;
  cfg.seg_size = (uint64_t)LGBM_WIRE_RING_DATA + req_cap + resp_cap;
  cfg.req_ctrl = LGBM_WIRE_RING_REQ_CTRL;
  cfg.req_offset = LGBM_WIRE_RING_DATA;
  cfg.req_capacity = (uint32_t)req_cap;
  cfg.resp_ctrl = LGBM_WIRE_RING_RESP_CTRL;
  cfg.resp_offset = (uint32_t)(LGBM_WIRE_RING_DATA + req_cap);
  cfg.resp_capacity = (uint32_t)resp_cap;
  uint8_t setup[LGBM_WIRE_HEADER_SIZE + LGBM_WIRE_RING_HEADER_SIZE];
  memcpy(setup + LGBM_WIRE_HEADER_SIZE, &cfg, sizeof cfg);
  put_header(setup, LGBM_WIRE_MSG_SHM_SETUP, "shm", 0, 0,
             setup + LGBM_WIRE_HEADER_SIZE, LGBM_WIRE_RING_HEADER_SIZE);
  if (write_full(sock, setup, sizeof setup) != 0 ||
      expect_shm_ok(sock) != 0) {
    fprintf(stderr, "shm setup rejected by server\n");
    close(sock);
    return 1;
  }
  int seg_fd = make_seg_fd(cfg.seg_size);
  if (seg_fd < 0) {
    fprintf(stderr, "segment create: %s\n", strerror(errno));
    close(sock);
    return 1;
  }
  uint8_t *seg = (uint8_t *)mmap(NULL, cfg.seg_size,
                                 PROT_READ | PROT_WRITE, MAP_SHARED,
                                 seg_fd, 0);
  if (seg == MAP_FAILED) {
    fprintf(stderr, "mmap: %s\n", strerror(errno));
    close(seg_fd);
    close(sock);
    return 1;
  }
  memcpy(seg, &cfg, sizeof cfg); /* segment header the server verifies */
  int efd_req = eventfd(0, EFD_NONBLOCK);
  int efd_resp = eventfd(0, EFD_NONBLOCK);
  if (efd_req < 0 || efd_resp < 0 ||
      send_three_fds(sock, seg_fd, efd_req, efd_resp) != 0 ||
      expect_shm_ok(sock) != 0) {
    fprintf(stderr, "shm fd pass failed\n");
    close(sock);
    return 1;
  }
  close(seg_fd); /* server holds its own reference now */

  ring_t req, resp;
  ring_init(&req, seg, cfg.req_ctrl, cfg.req_offset, cfg.req_capacity);
  ring_init(&resp, seg, cfg.resp_ctrl, cfg.resp_offset, cfg.resp_capacity);

  /* ---- pipelined produce/consume loop ---- */
  uint32_t req_payload = (uint32_t)(rows * ncols) * 4u;
  uint64_t frame_total = (uint64_t)LGBM_WIRE_HEADER_SIZE + req_payload;
  if (frame_total + 4 > req_cap) {
    fprintf(stderr, "request frame (%llu B) does not fit the ring\n",
            (unsigned long long)frame_total);
    return 1;
  }
  long *fl_probe = (long *)malloc((size_t)pipeline * sizeof(long));
  double *fl_t0 = (double *)malloc((size_t)pipeline * sizeof(double));
  int fl_head = 0, inflight = 0;
  double *lat = (double *)malloc((size_t)MAX_LAT * sizeof(double));
  long lat_n = 0;
  long sent = 0, completed = 0, rejected = 0, errors = 0;
  long checked = 0, mismatch = 0;
  long db_rings = 0, db_waits = 0, db_drains = 0;
  long win0_completed = 0, win0_syscalls = 0;
  double win0_t = 0.0;
  int snapped = 0;
  long probe = 0;
  double t0 = now_s();

  for (;;) {
    double now = now_s();
    int timeup = (now - t0) >= secs;
    if (!snapped && (now - t0) >= warmup) {
      snapped = 1;
      win0_completed = completed;
      win0_syscalls = db_rings + db_waits + db_drains;
      win0_t = now;
    }
    if (timeup && inflight == 0) break;
    /* fill the pipeline straight into the request ring */
    while (!timeup && inflight < pipeline) {
      uint64_t tail, pad;
      int64_t off = ring_reserve(&req, frame_total, &tail, &pad);
      if (off < 0) break; /* ring full: backpressure, drain a response */
      uint8_t *fp = req.data + off;
      float *dst = (float *)(fp + LGBM_WIRE_HEADER_SIZE);
      for (int r = 0; r < rows; r++) {
        long idx = (probe + r) % n_probes;
        memcpy(dst + (size_t)r * ncols, probes + idx * ncols,
               (size_t)ncols * 4);
      }
      put_header(fp, LGBM_WIRE_MSG_REQUEST, model_id, (uint32_t)rows,
                 (uint32_t)ncols, fp + LGBM_WIRE_HEADER_SIZE, req_payload);
      ring_publish(&req, tail, pad, frame_total);
      ring_bell(&req, efd_req, &db_rings);
      fl_probe[(fl_head + inflight) % pipeline] = probe;
      fl_t0[(fl_head + inflight) % pipeline] = now_s();
      inflight++;
      sent++;
      probe = (probe + rows) % n_probes;
    }
    if (inflight == 0) continue; /* time up between fills */
    /* consume the oldest response (server completes strictly in order) */
    if (ring_wait(&resp, efd_resp, sock, spin_s, &db_waits, &db_drains)
        != 0) {
      fprintf(stderr, "server went away mid-session\n");
      errors++;
      break;
    }
    uint64_t head = __atomic_load_n(resp.head, __ATOMIC_SEQ_CST);
    uint64_t tail = __atomic_load_n(resp.tail, __ATOMIC_SEQ_CST);
    uint64_t pos = head & resp.mask;
    uint64_t room = resp.cap - pos;
    uint64_t skip = 0;
    if (room < 4) {
      skip = room;
    } else {
      uint32_t mark;
      memcpy(&mark, resp.data + pos, 4);
      if (mark == LGBM_WIRE_RING_WRAP) skip = room;
    }
    pos = (head + skip) & resp.mask;
    uint64_t avail = tail - head - skip;
    LGBMWireFrameHeader rh;
    if (avail < sizeof rh) {
      fprintf(stderr, "torn response frame (%llu bytes)\n",
              (unsigned long long)avail);
      errors++;
      break;
    }
    memcpy(&rh, resp.data + pos, sizeof rh);
    uint64_t total = sizeof rh + rh.payload_len;
    if (memcmp(rh.magic, LGBM_WIRE_MAGIC, 4) != 0 ||
        rh.version != LGBM_WIRE_VERSION || rh.payload_len > MAX_PAYLOAD ||
        avail < total) {
      fprintf(stderr, "bad response frame in ring\n");
      errors++;
      break;
    }
    const uint8_t *pl = resp.data + pos + sizeof rh;
    if (crc32_buf(pl, rh.payload_len) != rh.crc32) {
      errors++;
      __atomic_store_n(resp.head, head + skip + total, __ATOMIC_SEQ_CST);
      break;
    }
    long oldest_probe = fl_probe[fl_head];
    double dt = now_s() - fl_t0[fl_head];
    if (rh.msg_type == LGBM_WIRE_MSG_RESPONSE) {
      completed++;
      if (lat_n < MAX_LAT) lat[lat_n++] = dt;
      if (expect && rh.n_rows == (uint32_t)rows &&
          rh.n_cols == (uint32_t)n_out) {
        int64_t gen;
        memcpy(&gen, pl, 8);
        if (gen == (int64_t)expect_gen) {
          const float *vals = (const float *)(pl + 32);
          for (int r = 0; r < rows; r++) {
            long idx = (oldest_probe + r) % n_probes;
            checked++;
            if (memcmp(vals + (size_t)r * n_out, expect + idx * n_out,
                       (size_t)n_out * 4) != 0)
              mismatch++;
          }
        }
      }
    } else if (rh.msg_type == LGBM_WIRE_MSG_REJECT) {
      rejected++;
      uint8_t retryable = rh.payload_len >= 8 ? pl[4] : 0;
      if (!retryable) {
        errors++;
        __atomic_store_n(resp.head, head + skip + total, __ATOMIC_SEQ_CST);
        break;
      }
    } else {
      errors++;
      __atomic_store_n(resp.head, head + skip + total, __ATOMIC_SEQ_CST);
      break;
    }
    __atomic_store_n(resp.head, head + skip + total, __ATOMIC_SEQ_CST);
    fl_head = (fl_head + 1) % pipeline;
    inflight--;
  }
  double elapsed = now_s() - t0;
  long syscalls = db_rings + db_waits + db_drains;
  long win_completed = snapped ? completed - win0_completed : completed;
  long win_syscalls = snapped ? syscalls - win0_syscalls : syscalls;
  double win_elapsed = snapped ? now_s() - win0_t : elapsed;

  qsort(lat, (size_t)lat_n, sizeof(double), cmp_double);
  double p50 = lat_n ? lat[(long)(0.50 * (double)(lat_n - 1))] : 0.0;
  double p99 = lat_n ? lat[(long)(0.99 * (double)(lat_n - 1))] : 0.0;
  printf("{\"mode\":\"shm\",\"conns\":1,\"rows\":%d,\"pipeline\":%d,"
         "\"elapsed_s\":%.3f,\"sent\":%ld,\"completed\":%ld,"
         "\"rejected\":%ld,\"errors\":%ld,"
         "\"verify_checked\":%ld,\"verify_mismatch\":%ld,"
         "\"req_per_sec\":%.1f,\"rows_per_sec\":%.1f,"
         "\"p50_ms\":%.4f,\"p99_ms\":%.4f,"
         "\"db_rings\":%ld,\"db_waits\":%ld,\"db_drains\":%ld,"
         "\"transport_syscalls\":%ld,"
         "\"win_completed\":%ld,\"win_syscalls\":%ld,"
         "\"win_elapsed_s\":%.3f}\n",
         rows, pipeline, elapsed, sent, completed, rejected, errors,
         checked, mismatch, (double)completed / elapsed,
         (double)(completed * rows) / elapsed, p50 * 1e3, p99 * 1e3,
         db_rings, db_waits, db_drains, syscalls, win_completed,
         win_syscalls, win_elapsed);
  close(sock);
  munmap(seg, cfg.seg_size);
  close(efd_req);
  close(efd_resp);
  return (errors > 0 || completed == 0 || mismatch > 0) ? 1 : 0;
}

/* ------------------------------------------------------ fastconfig mode */
typedef int (*create_fn)(const char *, int *, BoosterHandle *);
typedef int (*nclass_fn)(BoosterHandle, int *);
typedef int (*fastinit_fn)(BoosterHandle, int, int, int32_t, const char *,
                           int, FastConfigHandle *);
typedef int (*fast_fn)(FastConfigHandle, const void *, int64_t *, double *);
typedef int (*fastfree_fn)(FastConfigHandle);
typedef const char *(*err_fn)(void);

static int run_fastconfig(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: wire_client fastconfig LIB MODEL --probes F "
                    "--ncols N [--secs S]\n");
    return 2;
  }
  const char *lib_path = argv[2], *model_path = argv[3];
  const char *probes_path = NULL;
  int ncols = 0;
  double secs = 5.0;
  for (int arg = 4; arg < argc; arg++) {
    if (!strcmp(argv[arg], "--probes")) probes_path = argv[++arg];
    else if (!strcmp(argv[arg], "--ncols")) ncols = atoi(argv[++arg]);
    else if (!strcmp(argv[arg], "--secs")) secs = atof(argv[++arg]);
    else { fprintf(stderr, "unknown arg %s\n", argv[arg]); return 2; }
  }
  if (!probes_path || ncols <= 0) {
    fprintf(stderr, "--probes FILE and --ncols N are required\n");
    return 2;
  }
  long n_vals = 0;
  float *probes = load_f32(probes_path, &n_vals);
  if (!probes || n_vals % ncols) {
    fprintf(stderr, "bad probes file %s\n", probes_path);
    return 2;
  }
  long n_probes = n_vals / ncols;

  void *lib = dlopen(lib_path, RTLD_NOW | RTLD_LOCAL);
  if (!lib) {
    fprintf(stderr, "dlopen %s: %s\n", lib_path, dlerror());
    return 1;
  }
  create_fn create = (create_fn)dlsym(lib, "LGBM_BoosterCreateFromModelfile");
  nclass_fn nclass = (nclass_fn)dlsym(lib, "LGBM_BoosterGetNumClasses");
  fastinit_fn finit =
      (fastinit_fn)dlsym(lib, "LGBM_BoosterPredictForMatSingleRowFastInit");
  fast_fn fast = (fast_fn)dlsym(lib, "LGBM_BoosterPredictForMatSingleRowFast");
  fastfree_fn ffree = (fastfree_fn)dlsym(lib, "LGBM_FastConfigFree");
  err_fn lasterr = (err_fn)dlsym(lib, "LGBM_GetLastError");
  if (!create || !nclass || !finit || !fast || !ffree) {
    fprintf(stderr, "missing ABI symbols in %s\n", lib_path);
    return 1;
  }
  BoosterHandle booster = NULL;
  int n_iters = 0;
  if (create(model_path, &n_iters, &booster) != 0) {
    fprintf(stderr, "load failed: %s\n", lasterr ? lasterr() : "?");
    return 1;
  }
  int num_class = 1;
  nclass(booster, &num_class);
  FastConfigHandle fc = NULL;
  if (finit(booster, C_API_PREDICT_NORMAL, C_API_DTYPE_FLOAT32,
            (int32_t)ncols, "", -1, &fc) != 0) {
    fprintf(stderr, "FastInit failed: %s\n", lasterr ? lasterr() : "?");
    return 1;
  }
  double *out = (double *)malloc((size_t)num_class * sizeof(double));
  double checksum = 0.0;
  long calls = 0, errors = 0;
  double t0 = now_s();
  while (now_s() - t0 < secs) {
    const float *row = probes + (calls % n_probes) * ncols;
    int64_t out_len = 0;
    if (fast(fc, row, &out_len, out) != 0 || out_len != num_class) {
      errors++;
      break;
    }
    checksum += out[0];
    calls++;
  }
  double elapsed = now_s() - t0;
  ffree(fc);
  printf("{\"mode\":\"fastconfig\",\"num_iterations\":%d,"
         "\"num_class\":%d,\"calls\":%ld,\"errors\":%ld,"
         "\"elapsed_s\":%.3f,\"req_per_sec\":%.1f,\"checksum\":%.6f}\n",
         n_iters, num_class, calls, errors, elapsed,
         (double)calls / elapsed, checksum);
  return (errors > 0 || calls == 0) ? 1 : 0;
}

int main(int argc, char **argv) {
  crc_init();
  if (argc < 2) {
    fprintf(stderr,
            "usage: wire_client tcp HOST PORT ... | uds PATH ... | "
            "shm PATH ... | fastconfig LIB MODEL ...\n");
    return 2;
  }
  if (!strcmp(argv[1], "tcp") && argc >= 4) return run_socket(argc, argv, 0);
  if (!strcmp(argv[1], "uds") && argc >= 3) return run_socket(argc, argv, 1);
  if (!strcmp(argv[1], "shm") && argc >= 3) return run_shm(argc, argv);
  if (!strcmp(argv[1], "fastconfig")) return run_fastconfig(argc, argv);
  fprintf(stderr, "unknown mode %s\n", argv[1]);
  return 2;
}
