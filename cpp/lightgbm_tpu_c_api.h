/* C API for lightgbm-tpu's native model runtime.
 *
 * Deployment-side parity with the reference c_api.h (src/c_api.cpp): the
 * functions a serving stack needs — load a text model, inspect it, predict
 * dense matrices, save — implemented as a dependency-free C++17 shared
 * library.  TRAINING entry points (LGBM_DatasetCreate*, LGBM_BoosterUpdate*)
 * are deliberately absent: training in this framework is the JAX/TPU path
 * (Python `lightgbm_tpu` package or the CLI), and a C shim around a Python
 * interpreter would be slower and heavier than calling Python directly.
 * Constants and signatures mirror the reference so existing C/C++ serving
 * integrations recompile against this header unchanged.
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* BoosterHandle;

#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)

#define C_API_PREDICT_NORMAL (0)
#define C_API_PREDICT_RAW_SCORE (1)
#define C_API_PREDICT_LEAF_INDEX (2)

/* All functions return 0 on success, -1 on error (message via
 * LGBM_GetLastError). */

const char* LGBM_GetLastError();

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterFree(BoosterHandle handle);

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len);

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out_iteration);

int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                          const char* filename);

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int num_iteration,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str);

/* Dense-matrix prediction.
 * data: nrow*ncol values, row- or column-major; data_type selects
 * float/double.  predict_type: normal (objective transform applied), raw
 * score, or per-tree leaf indices.  num_iteration <= 0 means all.
 * out_result must hold nrow*num_class doubles (nrow*num_trees for
 * leaf_index); *out_len is set to the number written. */
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);

/* Sparse (CSR) prediction: indptr[nindptr] row offsets (int32 or int64 by
 * indptr_type using the C_API_DTYPE_* int codes below), indices[nelem]
 * column ids, data[nelem] values.  Absent entries are 0.0 (missing-zero
 * semantics apply).  num_col must cover the model's feature count. */
#define C_API_DTYPE_INT32 (2)
#define C_API_DTYPE_INT64 (3)

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem, int64_t num_col,
                              int predict_type, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);

#ifdef __cplusplus
}
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
