/* C API for lightgbm-tpu's native runtime.
 *
 * Parity with the reference c_api.h (src/c_api.cpp) on both sides of the
 * model lifecycle:
 *
 * - PREDICTION (load a text model, inspect, predict dense/CSR, save) is a
 *   dependency-free C++17 runtime — no Python, no JAX.
 * - TRAINING (LGBM_DatasetCreate*, LGBM_BoosterCreate/UpdateOneIter*,
 *   c_api.h:48-460 parity) drives this framework's real training engine
 *   in-process by embedding CPython lazily on first use: the compute path
 *   is XLA/TPU either way, and the C caller gets the same kernels as a
 *   Python caller.  Trained boosters flow through the SAME BoosterHandle
 *   as loaded ones — every predict/save entry point works on both (the
 *   trained model is re-parsed into the native runtime after each
 *   update, so predictions are bit-identical to a loaded model file).
 *
 * Constants and signatures mirror the reference so existing C/C++
 * integrations recompile against this header unchanged.
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* BoosterHandle;
typedef void* DatasetHandle;

#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)

#define C_API_PREDICT_NORMAL (0)
#define C_API_PREDICT_RAW_SCORE (1)
#define C_API_PREDICT_LEAF_INDEX (2)

#define C_API_FEATURE_IMPORTANCE_SPLIT (0)
#define C_API_FEATURE_IMPORTANCE_GAIN (1)

/* All functions return 0 on success, -1 on error (message via
 * LGBM_GetLastError). */

const char* LGBM_GetLastError();

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterFree(BoosterHandle handle);

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len);

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out_iteration);

/* Trees per iteration (reference LGBM_BoosterNumModelPerIteration):
 * 1 for binary/regression, num_class for multiclass — callers size
 * per-iteration tree arithmetic with this. */
int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                     int* out_tree_per_iteration);

/* Total number of weak models — trees — in the booster (reference
 * LGBM_BoosterNumberOfTotalModel): iterations x trees-per-iteration. */
int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models);

/* Feature names the model was trained with (reference
 * LGBM_BoosterGetFeatureNames).  Same fixed-buffer convention as
 * LGBM_BoosterGetEvalNames / LGBM_DatasetGetFeatureNames here: the
 * caller provides num_feature char* buffers of >=128 bytes; models
 * without stored names get the canonical Column_<i>. */
int LGBM_BoosterGetFeatureNames(BoosterHandle handle, int* out_len,
                                char** out_strs);

/* Leaf-level access (reference LGBM_BoosterGetLeafValue/SetLeafValue).
 * SetLeafValue is the serving-side patch primitive: it updates BOTH the
 * in-memory tree used by every predict entry point and the stored model
 * text (so SaveModel/SaveModelToString round-trips carry the patch).
 * Training boosters are read-only through this surface (their model is
 * resynced from the Python engine; patch via the Python Booster) —
 * SetLeafValue on one fails with an explanatory error. */
int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val);

int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val);

int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                          const char* filename);

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int num_iteration,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str);

/* JSON model dump (reference LGBM_BoosterDumpModel): same recursive
 * tree_structure schema as the Python binding's dump_model().  Two-call
 * protocol like SaveModelToString: *out_len is set to the required
 * buffer size (incl. NUL); the string is written when buffer_len
 * suffices.  num_iteration <= 0 dumps everything from start_iteration.
 * feature_importance_type is accepted for signature parity (importances
 * come from LGBM_BoosterFeatureImportance). */
int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str);

/* Per-feature importance (reference LGBM_BoosterFeatureImportance):
 * importance_type C_API_FEATURE_IMPORTANCE_SPLIT counts splits, _GAIN
 * sums non-negative split gains; out_results must hold num_feature
 * doubles.  num_iteration <= 0 uses every iteration. */
int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results);

/* Dense-matrix prediction.
 * data: nrow*ncol values, row- or column-major; data_type selects
 * float/double.  predict_type: normal (objective transform applied), raw
 * score, or per-tree leaf indices.  num_iteration <= 0 means all.
 * out_result must hold nrow*num_class doubles (nrow*num_trees for
 * leaf_index); *out_len is set to the number written. */
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);

/* Output-size calculator (reference LGBM_BoosterCalcNumPredict): the
 * number of doubles a predict over num_row rows will write — num_row *
 * num_class for normal/raw score, num_row * used_trees for leaf
 * indices.  Callers size out_result buffers with this instead of
 * duplicating the width arithmetic.  ADAPTATION: no start_iteration
 * parameter — this ABI's predict entry points take num_iteration only
 * (the pre-3.0 reference shape). */
int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int num_iteration,
                               int64_t* out_len);

/* One-row prediction (reference LGBM_BoosterPredictForMatSingleRow):
 * the stateless single-row spelling — per-call schema checks, no reuse
 * handle.  Latency-sensitive callers should use the FastInit/Fast pair
 * below, which pays validation once. */
int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const void* data, int data_type,
                                       int ncol, int is_row_major,
                                       int predict_type, int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result);

/* File-to-file prediction (reference c_api LGBM_BoosterPredictForFile /
 * src/application predictor.hpp): parse a delimited numeric data file
 * (CSV or TSV, auto-detected; label column removed — label_column=<idx>
 * in `parameter` overrides the default 0), predict every row, and write
 * one line per row to result_filename ("%.18g" values, tab-separated for
 * multi-output) — byte-identical to the Python CLI's
 * `task=predict` output for the same model and data. */
int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int num_iteration, const char* parameter,
                               const char* result_filename);

/* Single-row fast path (reference LGBM_BoosterPredictForMat
 * SingleRowFast): Init resolves the model, validates the schema and
 * allocates the row buffer ONCE; each subsequent call is one traversal
 * with zero setup.  The fast config is bound to one caller thread at a
 * time (the reference's contract).  num_iteration <= 0 means all. */
typedef void* FastConfigHandle;

int LGBM_BoosterPredictForMatSingleRowFastInit(
    BoosterHandle handle, int predict_type, int data_type, int32_t ncol,
    const char* parameter, int num_iteration, FastConfigHandle* out_fast);

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fast_config,
                                           const void* data,
                                           int64_t* out_len,
                                           double* out_result);

int LGBM_FastConfigFree(FastConfigHandle fast_config);

/* Binary serving wire protocol (ISSUE 16 data plane; runtime/wire.py).
 *
 * Little-endian length-prefixed frames over TCP or a Unix-domain
 * socket: a fixed 40-byte header, then payload_len payload bytes whose
 * CRC32 (zlib polynomial) is in the header.  Requests carry n_rows x
 * n_cols float32 features (payload_len == n_rows * n_cols * 4);
 * responses carry a 32-byte meta block (generation int64; latency,
 * queue_wait, batch_gather, device, drain float32; served_by,
 * compiled uint8; 2 pad) then n_rows x n_cols float32 predictions;
 * rejections carry retry_after_s float32, retryable uint8, reserved
 * uint8, reason_len uint16, then the reason bytes.
 *
 * The canonical field layout below is pinned token-for-token against
 * the Python HEADER_FIELDS tuple by helper/check_wire_abi.py (field
 * names + struct(3) format codes) — edit both together or the lint
 * fails the build.
 *
 * WIRE_FRAME_FIELDS: magic:4s version:B msg_type:B dtype:B flags:B
 *   model_id:16s n_rows:I n_cols:I payload_len:I crc32:I
 */
#define LGBM_WIRE_MAGIC "LGBW"
#define LGBM_WIRE_VERSION (1)
#define LGBM_WIRE_MSG_REQUEST (1)
#define LGBM_WIRE_MSG_RESPONSE (2)
#define LGBM_WIRE_MSG_REJECT (3)
#define LGBM_WIRE_MSG_SHM_SETUP (4)
#define LGBM_WIRE_MSG_SHM_OK (5)
#define LGBM_WIRE_DTYPE_F32 (0)
#define LGBM_WIRE_HEADER_SIZE (40)

#pragma pack(push, 1)
typedef struct LGBMWireFrameHeader {
  char magic[4];        /* "LGBW" */
  uint8_t version;      /* LGBM_WIRE_VERSION */
  uint8_t msg_type;     /* LGBM_WIRE_MSG_* */
  uint8_t dtype;        /* LGBM_WIRE_DTYPE_F32 */
  uint8_t flags;        /* reserved, 0 */
  char model_id[16];    /* NUL-padded model id */
  uint32_t n_rows;      /* rows in the feature/value matrix */
  uint32_t n_cols;      /* feature count (req) / outputs (resp) */
  uint32_t payload_len; /* bytes following the header */
  uint32_t crc32;       /* zlib CRC32 of the payload */
} LGBMWireFrameHeader;
#pragma pack(pop)

/* Shared-memory ring transport (ISSUE 20; runtime/shm_ring.py).
 *
 * A client on the UDS plane sends LGBM_WIRE_MSG_SHM_SETUP whose payload
 * is the 40-byte segment header below, receives an SHM_OK ack, passes
 * the segment fd plus two eventfd doorbells over the socket with
 * SCM_RIGHTS, and after a second SHM_OK the segment's two SPSC rings
 * carry ordinary wire frames with ZERO syscalls on the data path.
 * Segment layout: header at 0 (padded to 64), request-ring control at
 * req_ctrl, response-ring control at resp_ctrl (each 3 cache lines:
 * tail u64 | head u64 @ +64 | waiter u32 @ +128, free-running
 * counters, position = counter & (capacity-1)), ring data at
 * req_offset/resp_offset.  A frame that cannot fit before the segment
 * boundary is preceded by the 4-byte wrap marker LGBM_WIRE_RING_WRAP
 * (or an implicit skip when fewer than 4 bytes remain); frames are
 * always contiguous.  Capacities are powers of two.
 *
 * The field layout is pinned token-for-token against the Python
 * RING_HEADER_FIELDS tuple by helper/check_wire_abi.py — edit both
 * together or the lint fails the build.
 *
 * WIRE_RING_FIELDS: magic:4s version:B flags:B reserved:H seg_size:Q
 *   req_ctrl:I req_offset:I req_capacity:I resp_ctrl:I resp_offset:I
 *   resp_capacity:I
 */
#define LGBM_WIRE_RING_MAGIC "LGBR"
#define LGBM_WIRE_RING_VERSION (1)
#define LGBM_WIRE_RING_HEADER_SIZE (40)
#define LGBM_WIRE_RING_CTRL_SIZE (192)
#define LGBM_WIRE_RING_REQ_CTRL (64)
#define LGBM_WIRE_RING_RESP_CTRL (256)
#define LGBM_WIRE_RING_DATA (448)
#define LGBM_WIRE_RING_WRAP (0xFFFFFFFFu)
#define LGBM_WIRE_RING_DEFAULT_CAP (1u << 20)

#pragma pack(push, 1)
typedef struct LGBMWireRingHeader {
  char magic[4];           /* "LGBR" */
  uint8_t version;         /* LGBM_WIRE_RING_VERSION */
  uint8_t flags;           /* reserved, 0 */
  uint16_t reserved;       /* reserved, 0 */
  uint64_t seg_size;       /* total segment bytes */
  uint32_t req_ctrl;       /* request-ring control offset (64) */
  uint32_t req_offset;     /* request-ring data offset (448) */
  uint32_t req_capacity;   /* request-ring bytes, power of two */
  uint32_t resp_ctrl;      /* response-ring control offset (256) */
  uint32_t resp_offset;    /* response-ring data offset */
  uint32_t resp_capacity;  /* response-ring bytes, power of two */
} LGBMWireRingHeader;
#pragma pack(pop)

/* Sparse (CSR) prediction: indptr[nindptr] row offsets (int32 or int64 by
 * indptr_type using the C_API_DTYPE_* int codes below), indices[nelem]
 * column ids, data[nelem] values.  Absent entries are 0.0 (missing-zero
 * semantics apply).  num_col must cover the model's feature count. */
#define C_API_DTYPE_INT32 (2)
#define C_API_DTYPE_INT64 (3)

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem, int64_t num_col,
                              int predict_type, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);

/* Sparse (CSC) prediction (reference LGBM_BoosterPredictForCSC):
 * col_ptr[ncol_ptr] column offsets, indices[nelem] ROW ids,
 * data[nelem] values, num_row rows.  The column-major triplets are
 * scattered into a dense row-major buffer once (absent entries 0.0,
 * missing-zero semantics) and predicted with the same per-row kernel
 * as PredictForMat — bit-identical to transposing client-side. */
int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);

/* Single-row CSR fast path (reference PredictForCSRSingleRow): same
 * contract as PredictForCSR with nindptr == 2.  The dense scatter a
 * one-row CSR needs is already the per-row inner loop of the batch
 * entry point, so this delegates (the Fast-config mat trio is the
 * latency-optimized single-row path). */
int LGBM_BoosterPredictForCSRSingleRow(BoosterHandle handle,
                                       const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int predict_type,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result);

/* ---- training surface (embedded-engine; reference c_api.h:48-460) ----
 * parameters strings use the reference's "key=value key2=value2" form.
 * If the package is not importable from the default sys.path, set
 * LIGHTGBM_TPU_ROOT to the repo/site dir before the first training call.
 */

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               DatasetHandle reference, DatasetHandle* out);

int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters, DatasetHandle reference,
                              DatasetHandle* out);

/* ---- zero-copy streaming ingest (reference c_api.h:48-232 dataset-
 * from-memory block; lightgbm_tpu/io/stream.py is the engine).  CSR/CSC
 * creation takes the standard compressed-sparse triplets; absent entries
 * are 0.0 (so zero_as_missing applies to them exactly like a parsed
 * file's explicit zeros).  indptr/col_ptr use C_API_DTYPE_INT32/INT64;
 * data uses FLOAT32/FLOAT64.  `reference` aligns the new dataset to an
 * existing dataset's bin mappers (validation semantics). */

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              DatasetHandle reference, DatasetHandle* out);

int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, const char* parameters,
                              DatasetHandle reference, DatasetHandle* out);

/* Streaming creation: declare the total row count up front against a
 * constructed reference dataset, then push row chunks (dense or CSR) at
 * arbitrary start_row offsets.  The reference's bin mappers are FIXED at
 * creation and every pushed chunk is binned immediately into packed
 * integer storage and dropped — memory is bounded by the uint8/uint16
 * bin matrix, not the raw float stream.  The dataset finalizes lazily
 * when first used (BoosterCreate etc.); an incomplete stream fails then
 * with the missing row range named. */
int LGBM_DatasetCreateByReference(DatasetHandle reference,
                                  int64_t num_total_row, DatasetHandle* out);

int LGBM_DatasetPushRows(DatasetHandle dataset, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row);

int LGBM_DatasetPushRowsByCSR(DatasetHandle dataset, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row);

/* Row subset sharing the parent's bin mappers/bundles (reference
 * LGBM_DatasetGetSubset): used_row_indices must be sorted ascending and
 * unique.  Works on any dataset handle, including ones whose raw chunks
 * were dropped by the streaming path (the gather runs on binned
 * storage). */
int LGBM_DatasetGetSubset(DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out);

/* Persist the constructed dataset to the binary cache format
 * (version-stamped; LGBM_DatasetCreateFromFile loads it back directly,
 * skipping parse + find-bin + bundling). */
int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename);

/* Debug dump of the constructed dataset to a text file (reference
 * LGBM_DatasetDumpText, adapted content: header lines — num_data,
 * num_features, feature names, per-feature bin counts, label presence —
 * followed by the BINNED storage rows, i.e. the post-bundling integer
 * bin matrix training actually consumes). */
int LGBM_DatasetDumpText(DatasetHandle handle, const char* filename);

/* Feature names (reference Set/GetFeatureNames).  Get follows the
 * GetEvalNames contract: out_strs must hold num_feature pointers to
 * buffers of at least 128 bytes each. */
int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names);

int LGBM_DatasetGetFeatureNames(DatasetHandle handle, char** feature_names,
                                int* num_feature_names);

/* field_name: label / weight / init_score / group (reference SetField). */
int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type);

/* Generic field getter (reference GetField).  *out_ptr points at a
 * buffer owned by the dataset handle, valid until the next GetField
 * call on the same handle or DatasetFree.  *out_type is a C_API_DTYPE_*
 * code: label/weight -> float32, init_score -> float64, group -> int32
 * CUMULATIVE query boundaries (num_queries + 1 entries — the
 * reference's query_boundaries_ layout, not the sizes SetField takes). */
int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr,
                         int* out_type);

/* Bin count of one feature after construction (reference
 * LGBM_DatasetGetFeatureNumBin; extension relative to the canonical
 * 58-point parity list in helper/check_abi.py). */
int LGBM_DatasetGetFeatureNumBin(DatasetHandle handle, int feature_idx,
                                 int32_t* out);

/* Concatenate nmat row-major (or column-major) blocks sharing ncol into
 * one dataset (reference CreateFromMats): data[i] is an nrow[i] x ncol
 * block of data_type. */
int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data,
                               int data_type, int32_t* nrow, int32_t ncol,
                               int is_row_major, const char* parameters,
                               DatasetHandle reference,
                               DatasetHandle* out);

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out);

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out);

int LGBM_DatasetFree(DatasetHandle handle);

int LGBM_BoosterCreate(DatasetHandle train_data, const char* parameters,
                       BoosterHandle* out);

int LGBM_BoosterAddValidData(BoosterHandle handle, DatasetHandle valid_data);

/* One boosting iteration; *is_finished = 1 when no further splits met the
 * requirements (reference semantics). */
int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);

/* Custom objective: grad/hess are num_data * num_class float32. */
int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int* is_finished);

int LGBM_BoosterRollbackOneIter(BoosterHandle handle);

/* Reset booster parameters mid-training (reference
 * LGBM_BoosterResetParameter -> Booster::ResetConfig): "key=value ..."
 * string; e.g. a learning_rate change takes effect on the next
 * UpdateOneIter.  Training boosters only. */
int LGBM_BoosterResetParameter(BoosterHandle handle, const char* parameters);

/* Refit the model's tree structures to new data (reference Booster.refit
 * / gbdt.cpp RefitTree + FitByExistingTree): every split is kept, leaf
 * values are recomputed from the new data's gradients as
 * leaf = decay*old + (1-decay)*new*shrinkage, iterating so later trees
 * see the refit scores of earlier ones.  ADAPTATION of the reference
 * signature: the reference passes pre-computed leaf assignments
 * (leaf_preds) against a separately merged booster; here the new window
 * travels directly (data: nrow*ncol row-major float64, label: nrow
 * float32) and leaf assignments are computed internally — the embedded
 * engine owns both halves, which is also the path the online trainer's
 * refit mode uses.  Training boosters only; the handle's model is
 * REPLACED in place (subsequent predict/save/dump see the refit model;
 * to continue boosting, create a fresh training booster from it). */
int LGBM_BoosterRefit(BoosterHandle handle, const double* data,
                      const float* label, int32_t nrow, int32_t ncol);

/* Metric values for data_idx (0 = training, i > 0 = i-th valid set). */
int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results);

/* Number of evaluation metrics — callers size LGBM_BoosterGetEval's
 * out_results (and GetEvalNames' out_strs) with this, matching the
 * reference pairing (c_api.h GetEvalCounts/GetEvalNames). */
int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len);

/* Metric names; out_strs must hold GetEvalCounts pointers to buffers of
 * at least 128 bytes each (the reference's unsized-strcpy contract). */
int LGBM_BoosterGetEvalNames(BoosterHandle handle, int* out_len,
                             char** out_strs);

/* Inner prediction buffer (reference LGBM_BoosterGetNumPredict /
 * LGBM_BoosterGetPredict): the engine's CURRENT scores for the training
 * data (data_idx = 0) or the data_idx-th validation set, maintained
 * incrementally across UpdateOneIter — read, never re-predicted.  The
 * objective transform is applied (sigmoid/softmax/...; raw for
 * objectives without one) and the layout is class-major
 * ([class][row], num_class * num_data doubles), matching the
 * reference's GBDT::GetPredictAt.  Training boosters only: a loaded
 * model has no attached data.  GetNumPredict sizes out_result for
 * GetPredict.  NOTE: the engine maintains training scores in float32
 * on device, so these values agree with an offline float64 predict to
 * f32 precision (~1e-7 relative), not bit-for-bit. */
int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len);

int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result);

/* Distributed bootstrap (reference Network::Init / LGBM_NetworkInit):
 * machines = "ip:port,ip:port,...".  Maps onto jax.distributed — see
 * docs/DISTRIBUTED.md.  The function-pointer transport variant
 * (LGBM_NetworkInitWithFunctions) has no analogue: collectives are
 * compiled into the XLA program and cannot be user-supplied. */
int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines);

int LGBM_NetworkFree();

#ifdef __cplusplus
}
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
