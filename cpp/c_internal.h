/* Internal seam between the dependency-free prediction runtime
 * (lib_lightgbm_tpu.so, c_api.cc) and the embedded-Python training
 * backend (lib_lightgbm_tpu_train.so, c_train.cc).
 *
 * Both booster kinds travel through the SAME public BoosterHandle (the
 * reference c_api has one handle type for loaded and trained boosters);
 * a leading magic word distinguishes them so the shared entry points can
 * dispatch.  The training library REGISTERS its dispatch hooks into the
 * base library from an ELF constructor at load time — the base library
 * carries no Python (or training-library) dependency, so prediction-only
 * deployments stay dependency-free, exactly as the public header
 * advertises. */
#ifndef LIGHTGBM_TPU_C_INTERNAL_H_
#define LIGHTGBM_TPU_C_INTERNAL_H_

#include <cstdint>
#include <string>

namespace lgbm_tpu_internal {

// ASCII tags: "NBST" native booster, "TBST" training booster, "TDAT"
// training dataset.  Every handle struct starts with one.
constexpr uint32_t kNativeBoosterMagic = 0x5453424Eu;
constexpr uint32_t kTrainBoosterMagic = 0x54534254u;
constexpr uint32_t kTrainDatasetMagic = 0x54414454u;

inline uint32_t HandleMagic(const void* h) {
  return h ? *static_cast<const uint32_t*>(h) : 0u;
}

// Hooks the training library provides to the base library.
struct TrainHooks {
  // Current model parsed into a native booster (cached; re-synced after
  // every update/rollback).  Returns nullptr on error (message set).
  // On success the handle's model lock is held SHARED by the calling
  // thread: the returned Model* stays alive across the caller's whole
  // predict/save, even if a concurrent update marks the cache dirty and
  // another thread resyncs — the resync's free waits for readers.  The
  // caller MUST pair every successful call with booster_native_release
  // (c_api.cc's ModelRef does this via RAII).
  void* (*booster_native)(void* h);
  // Drop the shared model lock taken by a successful booster_native.
  void (*booster_native_release)(void* h);
  int (*booster_free)(void* h);
  int (*booster_current_iteration)(void* h, int* out);
};

// --- implemented in c_api.cc (the base library) ---
void SetLastError(const std::string& msg);
// Called once from the training library's ELF constructor.
void RegisterTrainHooks(const TrainHooks* hooks);
const TrainHooks* GetTrainHooks();

inline bool IsTrainBooster(const void* h) {
  return HandleMagic(h) == kTrainBoosterMagic && GetTrainHooks() != nullptr;
}

}  // namespace lgbm_tpu_internal

// --- native text ingest (ingest.cc, same base library) ---
// The mmap + OpenMP delimited parser behind lightgbm_tpu/io/parser.py's
// fast path; LGBM_BoosterPredictForFile reuses it so the C file-predict
// parses byte-identically to the Python CLI.
extern "C" {
long long LGBMT_CountRows(const char* path, int has_header, char sep);
// rc 0 ok, -1 I/O error, -2 row-count mismatch, -4 ragged rows,
// -5 non-numeric token.  X is [n_rows, n_cols-1] (label column removed).
int LGBMT_ParseDense(const char* path, char sep, int has_header,
                     long long n_rows, int n_cols, int label_col,
                     double* X, double* y);
}

#endif  /* LIGHTGBM_TPU_C_INTERNAL_H_ */
