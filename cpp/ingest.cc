// Native text ingest: mmap + OpenMP delimited parse and bin encode.
//
// Role parity with the reference's native DatasetLoader/Parser pipeline
// (src/io/dataset_loader.cpp LoadFromFile + parser.cpp CSV/TSV parsers +
// bin.h ValueToBin:452-488): the reference parses training text and pushes
// binned values with native code; these entry points give the Python
// loader the same native fast path (ctypes, see lightgbm_tpu/io/parser.py
// and io/binning.py), with the tolerant Python parsers as the fallback.
//
// Scope: plain numeric CSV/TSV (no quoting — same contract as the pandas
// fast path it replaces); LibSVM stays in Python.

#include <algorithm>
#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
  Mapped m;
  m.fd = ::open(path, O_RDONLY);
  if (m.fd < 0) return m;
  struct stat st;
  if (::fstat(m.fd, &st) != 0 || st.st_size == 0) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  void* p = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
  if (p == MAP_FAILED) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  m.data = static_cast<const char*>(p);
  m.size = st.st_size;
  return m;
}

void unmap_file(Mapped& m) {
  if (m.data) ::munmap(const_cast<char*>(m.data), m.size);
  if (m.fd >= 0) ::close(m.fd);
  m.data = nullptr;
  m.fd = -1;
}

bool line_blank(const char* b, const char* e, char sep) {
  for (const char* p = b; p < e; ++p) {
    if (*p == sep) return false;  // separators make it a data row of
                                  // empty fields, not a blank line
    if (!std::isspace(static_cast<unsigned char>(*p))) return false;
  }
  return true;
}

// skip the header (the first NON-BLANK line — the Python sniffer ignores
// leading blank lines) if present; returns body start
const char* body_start(const Mapped& m, int has_header, char sep) {
  const char* p = m.data;
  const char* end = m.data + m.size;
  if (!has_header) return p;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* le = nl ? nl : end;
    bool blank = line_blank(p, le, sep);
    p = nl ? nl + 1 : end;
    if (!blank) break;  // consumed the header line
  }
  return p;
}

// missing markers of the Python parsers: '', na, nan, null, n/a, none, ?
bool is_missing_token(const char* b, const char* e) {
  while (b < e && std::isspace(static_cast<unsigned char>(*b))) ++b;
  while (e > b && std::isspace(static_cast<unsigned char>(e[-1]))) --e;
  size_t len = e - b;
  if (len == 0) return true;
  char buf[8];
  if (len >= sizeof(buf)) return false;
  for (size_t i = 0; i < len; ++i)
    buf[i] = std::tolower(static_cast<unsigned char>(b[i]));
  buf[len] = 0;
  return !strcmp(buf, "na") || !strcmp(buf, "nan") || !strcmp(buf, "null") ||
         !strcmp(buf, "n/a") || !strcmp(buf, "none") || !strcmp(buf, "?");
}

double strtod_token(const char* b, const char* e) {
  // terminated copy for strtod (overflow/underflow parity with python
  // float(): 1e400 -> inf, 1e-400 -> 0.0); stack buffer for the common
  // case, heap for pathological token lengths (never truncate — a
  // truncated '1e400...' would parse to a wrong FINITE value)
  size_t len = e - b;
  char buf[64];
  std::string heap;
  const char* src;
  if (len < sizeof(buf)) {
    memcpy(buf, b, len);
    buf[len] = 0;
    src = buf;
  } else {
    heap.assign(b, e);
    src = heap.c_str();
  }
  char* endp = nullptr;
  double v = std::strtod(src, &endp);
  if (endp != src + len) return NAN;
  return v;
}

double parse_token(const char* b, const char* e, bool* bad) {
  // trim; empty/marker tokens -> NaN
  while (b < e && std::isspace(static_cast<unsigned char>(*b))) ++b;
  while (e > b && std::isspace(static_cast<unsigned char>(e[-1]))) --e;
  if (b == e) return NAN;
  const char* p = b;
  if (*p == '+') ++p;  // from_chars rejects a leading '+'; python allows it
#if defined(__cpp_lib_to_chars)
  // std::from_chars: correctly rounded like strtod/python float() (exact
  // bin parity with the Python parsers) at several times the speed, and
  // it takes an explicit [b, e) range — no NUL needed on the mmap.
  double v = 0.0;
  auto r = std::from_chars(p, e, v);
  if (r.ec == std::errc() && r.ptr == e) return v;
  if (r.ec == std::errc::result_out_of_range && r.ptr == e)
    return strtod_token(p, e);  // python parity: inf / 0.0, not NaN
#else
  double v = strtod_token(p, e);
  if (!std::isnan(v) || is_missing_token(b, e)) return v;
#endif
  if (is_missing_token(b, e)) return NAN;
  // a real text token (not a missing marker): the Python parser would
  // RAISE here — flag it so the wrapper falls back and the user sees
  // the loud error instead of silently training on NaNs
  *bad = true;
  return NAN;
}

// Split the body into per-thread ranges aligned to line starts, then count
// non-blank lines per range; prefix sums give each range's first row id.
struct Ranges {
  std::vector<const char*> begin;
  std::vector<const char*> end;
  std::vector<long long> first_row;
  long long total_rows = 0;
};

Ranges make_ranges(const char* body, const char* eof, int n_threads,
                   char sep) {
  Ranges r;
  size_t len = eof - body;
  std::vector<const char*> starts(n_threads + 1);
  starts[0] = body;
  for (int t = 1; t < n_threads; ++t) {
    const char* p = body + (len * t) / n_threads;
    const char* nl = static_cast<const char*>(memchr(p, '\n', eof - p));
    starts[t] = nl ? nl + 1 : eof;
  }
  starts[n_threads] = eof;
  std::vector<long long> counts(n_threads, 0);
#pragma omp parallel for schedule(static)
  for (int t = 0; t < n_threads; ++t) {
    const char* p = starts[t];
    const char* e = starts[t + 1];
    long long c = 0;
    while (p < e) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', e - p));
      const char* le = nl ? nl : e;
      if (!line_blank(p, le, sep)) ++c;
      p = nl ? nl + 1 : e;
    }
    counts[t] = c;
  }
  r.begin.resize(n_threads);
  r.end.resize(n_threads);
  r.first_row.resize(n_threads);
  long long acc = 0;
  for (int t = 0; t < n_threads; ++t) {
    r.begin[t] = starts[t];
    r.end[t] = starts[t + 1];
    r.first_row[t] = acc;
    acc += counts[t];
  }
  r.total_rows = acc;
  return r;
}

int num_threads() {
#ifdef _OPENMP
  return std::max(1, omp_get_max_threads());
#else
  return 1;
#endif
}

}  // namespace

extern "C" {

// Number of non-blank data rows (excluding the header), or -1 on error.
long long LGBMT_CountRows(const char* path, int has_header, char sep) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* body = body_start(m, has_header, sep);
  Ranges r = make_ranges(body, m.data + m.size, num_threads(), sep);
  long long n = r.total_rows;
  unmap_file(m);
  return n;
}

// Parse a delimited numeric file into X [n_rows, n_cols-1] row-major f64
// (label column removed) and y [n_rows].  Short lines are tolerated
// (missing fields stay NaN); lines with MORE than n_cols fields abort
// with rc -4 so the Python fallback's widest-row semantics apply.
// rc 0 ok, -1 I/O error, -2 row-count mismatch (file changed between
// calls).
int LGBMT_ParseDense(const char* path, char sep, int has_header,
                     long long n_rows, int n_cols, int label_col,
                     double* X, double* y) {
  // NOTE: the file is memchr-scanned once in CountRows and once more by
  // this make_ranges — redundant but cheap next to the field parse
  // (SIMD memchr runs at several GB/s vs ~0.2 GB/s for number parsing)
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* body = body_start(m, has_header, sep);
  Ranges r = make_ranges(body, m.data + m.size, num_threads(), sep);
  if (r.total_rows != n_rows) {
    unmap_file(m);
    return -2;
  }
  const int n_feat = n_cols - 1;
  const long long xbytes_row = n_feat;
  int n_ranges = static_cast<int>(r.begin.size());
  int ragged = 0;
  int bad_token = 0;
#pragma omp parallel for schedule(static) reduction(|| : ragged) \
    reduction(|| : bad_token)
  for (int t = 0; t < n_ranges; ++t) {
    const char* p = r.begin[t];
    const char* e = r.end[t];
    long long row = r.first_row[t];
    while (p < e) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', e - p));
      const char* le = nl ? nl : e;
      if (!line_blank(p, le, sep)) {
        double* xrow = X + row * xbytes_row;
        for (int j = 0; j < n_feat; ++j) xrow[j] = NAN;
        int col = 0;
        bool consumed_all = false;
        const char* fb = p;
        while (fb <= le && col < n_cols) {
          const char* fe = static_cast<const char*>(
              memchr(fb, sep, le - fb));
          if (fe == nullptr) fe = le;
          bool bad = false;
          double v = parse_token(fb, fe, &bad);
          if (bad) bad_token = 1;
          if (col == label_col) {
            y[row] = v;
          } else {
            int j = col < label_col ? col : col - 1;
            xrow[j] = v;
          }
          ++col;
          if (fe == le) {
            consumed_all = true;
            break;
          }
          fb = fe + 1;
        }
        // fields beyond n_cols (even empty trailing ones): bail out so
        // the Python fallback's widest-row semantics decide the schema
        if (!consumed_all && col >= n_cols) ragged = 1;
        ++row;
      }
      p = nl ? nl + 1 : e;
    }
  }
  unmap_file(m);
  if (ragged) return -4;
  return bad_token ? -5 : 0;
}

// Numerical ValueToBin (bin.h:452-488 semantics, matching
// BinMapper.values_to_bins): for each feature f with upper bounds
// bounds[offs[f] : offs[f]+cnts[f]]:
//   missing_type == 2 (NaN): NaN -> num_bin-1; values searchsorted-left
//     over bounds[:cnt-2] (when num_bin >= 2)
//   else: NaN treated as 0.0; searchsorted-left over bounds[:cnt-1]
// X is row-major [n, F]; out is FEATURE-major uint8 [F, n_stride] (the
// dataset's storage layout).  Features with trivial[f] != 0 are skipped.
// rc 0 ok, -3 if any num_bin > 256 (caller must use the Python path).
int LGBMT_EncodeBins(const double* X, long long n, int F,
                     const double* bounds, const long long* offs,
                     const int* cnts, const int* missing_type,
                     const int* num_bin, const int* trivial,
                     unsigned char* out, long long n_stride) {
  for (int f = 0; f < F; ++f)
    if (!trivial[f] && num_bin[f] > 256) return -3;
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    const double* xrow = X + i * F;
    for (int f = 0; f < F; ++f) {
      if (trivial[f]) continue;
      const double* b = bounds + offs[f];
      const int cnt = cnts[f];
      const bool nan_mode = missing_type[f] == 2;
      int hi = nan_mode ? (num_bin[f] >= 2 ? cnt - 2 : 0) : cnt - 1;
      if (hi < 0) hi = 0;
      double v = xrow[f];
      int idx;
      if (std::isnan(v)) {
        idx = nan_mode ? num_bin[f] - 1
                       : static_cast<int>(std::lower_bound(b, b + hi, 0.0) - b);
      } else {
        idx = static_cast<int>(std::lower_bound(b, b + hi, v) - b);
      }
      out[static_cast<long long>(f) * n_stride + i] =
          static_cast<unsigned char>(idx);
    }
  }
  return 0;
}

}  // extern "C"
