#!/bin/bash
# Regenerate golden test fixtures using the reference CLI built from /root/reference.
# Usage: bash helper/gen_goldens.sh
set -e
ROOT=$(cd "$(dirname "$0")/.." && pwd)
REF=/root/reference
BUILD=$ROOT/.refbuild
if [ ! -x $BUILD/lightgbm ]; then
  mkdir -p $BUILD && cd $BUILD
  cmake $REF -DCMAKE_BUILD_TYPE=Release -DUSE_OPENMP=ON > cmake.log 2>&1
  make -j8 > make.log 2>&1
  # reference CMake drops outputs into the source tree; relocate them
  mv $REF/lightgbm $REF/lib_lightgbm.so $BUILD/ 2>/dev/null || true
fi
LGBM=$BUILD/lightgbm
mkdir -p $ROOT/.golden/binary && cd $ROOT/.golden/binary
$LGBM task=train objective=binary metric=binary_logloss,auc metric_freq=1 is_training_metric=true \
  max_bin=255 data=$REF/examples/binary_classification/binary.train \
  valid_data=$REF/examples/binary_classification/binary.test \
  num_trees=20 learning_rate=0.1 num_leaves=31 output_model=golden_model.txt
$LGBM task=predict data=$REF/examples/binary_classification/binary.test \
  input_model=golden_model.txt output_result=golden_pred.txt
