#!/usr/bin/env python
"""Blocking-fetch static lint (ISSUE 9 satellite).

The sync-audit seam (`lightgbm_tpu/runtime/syncs.py`) is only a real
instrument if every blocking device->host observation actually goes
through it.  This lint pins that property statically for the four files
the audit covers — `boosting/gbdt.py`, `basic.py`,
`runtime/resilience.py`, `models/device_predictor.py`:

1. no direct ``jax.device_get(...)`` / ``jax.block_until_ready(...)`` /
   ``<x>.block_until_ready()`` / bare ``device_get(...)`` call — those
   bypass the counters (``syncs.device_get`` is exempt: the seam itself);
2. no ``np.asarray(...)`` / ``np.array(...)`` applied to an expression
   that names a known device-resident source (the implicit-fetch
   spelling of the same stall).  Static analysis cannot type arbitrary
   expressions, so this arm matches a curated marker list — it is a
   tripwire for the common regressions, not a proof;
3. a known-legacy call site may be excused through the allowlist file
   (``helper/check_syncs_allowlist.txt``: ``<basename>:<regex>`` lines)
   so a deliberate exception is visible and reviewed, never silent.

Run standalone (``python helper/check_syncs.py``; exit 1 on drift) or
through the tier-1 pin in ``tests/test_check_syncs.py`` (which also
pins that the lint CATCHES each violation class — the drift-detection
negatives, same pattern as ``tests/test_check_abi.py``).
"""
from __future__ import annotations

import os
import re
import sys
import tokenize
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "lightgbm_tpu")

#: the audited files: everything the ISSUE-5 sync audit routed through
#: the seam and must not regress out of it
SCAN_FILES = (
    os.path.join(PKG, "boosting", "gbdt.py"),
    os.path.join(PKG, "basic.py"),
    os.path.join(PKG, "runtime", "resilience.py"),
    os.path.join(PKG, "models", "device_predictor.py"),
)

ALLOWLIST_PATH = os.path.join(REPO, "helper", "check_syncs_allowlist.txt")

#: direct blocking-fetch spellings.  `syncs.device_get(` survives rule 3
#: because the bare-name rule refuses a preceding ``.`` or word char.
_DIRECT_RULES: Tuple[Tuple[str, re.Pattern], ...] = (
    ("jax.device_get", re.compile(r"\bjax\.device_get\s*\(")),
    ("jax.block_until_ready",
     re.compile(r"\bjax\.block_until_ready\s*\(")),
    ("method block_until_ready",
     re.compile(r"\.block_until_ready\s*\(")),
    ("bare device_get", re.compile(r"(?<![\w.])device_get\s*\(")),
    ("bare block_until_ready",
     re.compile(r"(?<![\w.])block_until_ready\s*\(")),
)

#: identifiers that are device-resident in the audited files; an
#: np.asarray over one of these is an implicit blocking fetch
_DEVICE_MARKERS = ("jnp.", "self.score", "eng.score", "engine.score",
                   ".payload", "fs.aux", "leaf_out", "tree_dev")
_NP_CAST = re.compile(r"\bnp\.(?:as)?array\s*\(")


def load_allowlist(path: str = ALLOWLIST_PATH) -> List[Tuple[str, re.Pattern]]:
    """``<basename>:<regex>`` entries; blank lines and # comments skipped."""
    entries: List[Tuple[str, re.Pattern]] = []
    try:
        with open(path) as fh:
            for raw in fh:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                fname, _, pattern = line.partition(":")
                entries.append((fname.strip(), re.compile(pattern.strip())))
    except OSError:
        pass
    return entries


def _allowed(fname: str, line: str,
             allowlist: List[Tuple[str, re.Pattern]]) -> bool:
    return any(f == fname and rx.search(line) for f, rx in allowlist)


#: H2D upload spelling: jnp.asarray(np.asarray(host_data, ...)) moves
#: bytes TOWARD the device — the opposite direction of the stall the
#: lint hunts — and must not trip the np-cast rule
_UPLOAD = re.compile(r"jnp\.(?:as)?array\(np\.")


def _code_lines(path: str) -> Dict[int, str]:
    """line number -> source with comments and string literals removed
    (token-level, so docstrings mentioning device_get never match).
    Tokens are joined bare — the rules' regexes are written for that."""
    drop = {tokenize.COMMENT, tokenize.STRING, tokenize.NL,
            tokenize.NEWLINE, tokenize.INDENT, tokenize.DEDENT,
            tokenize.ENCODING, tokenize.ENDMARKER}
    lines: Dict[int, List[str]] = {}
    with open(path, "rb") as fh:
        for tok in tokenize.tokenize(fh.readline):
            if tok.type in drop:
                continue
            lines.setdefault(tok.start[0], []).append(tok.string)
    out: Dict[int, str] = {}
    for no, parts in lines.items():
        joined = " ".join(parts)
        # keep word boundaries between identifiers but re-fuse the
        # attribute/call punctuation the rules' regexes expect
        joined = re.sub(r"\s*\.\s*", ".", joined)
        joined = re.sub(r"\s*\(\s*", "(", joined)
        out[no] = joined
    return out


def scan_file(path: str,
              allowlist: List[Tuple[str, re.Pattern]]) -> List[str]:
    problems: List[str] = []
    fname = os.path.basename(path)
    with open(path) as fh:
        raw_lines = fh.read().splitlines()
    for no, code in sorted(_code_lines(path).items()):
        raw = raw_lines[no - 1] if no <= len(raw_lines) else code
        if "syncs." in code:
            continue                    # routed through the seam
        for label, rx in _DIRECT_RULES:
            if rx.search(code):
                if _allowed(fname, raw, allowlist):
                    break
                problems.append(
                    "%s:%d: direct blocking fetch (%s) outside "
                    "runtime/syncs.py: %s"
                    % (fname, no, label, raw.strip()))
                break
        else:
            if _NP_CAST.search(code) and not _UPLOAD.search(code) and \
                    any(m in code for m in _DEVICE_MARKERS):
                if not _allowed(fname, raw, allowlist):
                    problems.append(
                        "%s:%d: np.asarray over a device-resident source "
                        "(implicit blocking fetch): %s"
                        % (fname, no, raw.strip()))
    return problems


def run(files=SCAN_FILES, allowlist_path: str = ALLOWLIST_PATH) -> List[str]:
    """Returns the list of drift problems (empty = clean)."""
    allowlist = load_allowlist(allowlist_path)
    problems: List[str] = []
    for path in files:
        if not os.path.exists(path):
            problems.append("audited file missing: %s" % path)
            continue
        problems.extend(scan_file(path, allowlist))
    return problems


def main(argv=None) -> int:
    problems = run()
    print("check_syncs: scanned %d files, %d problem(s)"
          % (len(SCAN_FILES), len(problems)))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_syncs: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
