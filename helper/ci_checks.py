#!/usr/bin/env python
"""One-command runner for every static lint the repo carries (ISSUE 13
satellite).

Six lints guard cross-file invariants — the C-ABI/PARITY.md count
(`check_abi`), blocking fetches outside runtime/syncs.py
(`check_syncs`), raw ``jax.jit`` bypassing the xla_obs ledger
(`check_xla_sites`), unarmed FAULT_TABLE entries
(`check_fault_coverage`), unarmed METRIC_TABLE families
(`check_metric_coverage`, ISSUE 14) and the binary wire-frame header
layout pinned C-vs-Python (`check_wire_abi`, ISSUE 16) — but until
now each had to be invoked separately, so a PR could green five and
forget the sixth.
This runner invokes all of them in one process and fails if ANY fails:

    python helper/ci_checks.py            # exit 0 = all lints green

Each check's own ``main()`` is the single source of truth (no logic is
duplicated here); the runner only sequences them and aggregates the
verdict.  ``tests/test_ci_checks.py`` pins under tier-1 that the
committed tree passes the full set through THIS entry point, so the
one-command contract cannot silently rot.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Tuple

HELPER_DIR = os.path.dirname(os.path.abspath(__file__))

#: (module name, human label) — every static lint the repo has; a new
#: lint lands by adding its row here (test_ci_checks pins membership)
CHECKS: Tuple[Tuple[str, str], ...] = (
    ("check_abi", "C-ABI export count vs PARITY.md"),
    ("check_syncs", "blocking fetches outside runtime/syncs.py"),
    ("check_xla_sites", "raw jax.jit bypassing the xla_obs ledger"),
    ("check_fault_coverage", "FAULT_TABLE entries unarmed by any test"),
    ("check_metric_coverage",
     "METRIC_TABLE families unarmed by any instrument call site"),
    ("check_wire_abi",
     "binary wire-frame header layout C header vs runtime/wire.py"),
)


def run_all() -> Dict[str, int]:
    """{check name: exit code} for every lint, always running all of
    them (a later lint's verdict must not hide behind an earlier
    failure)."""
    if HELPER_DIR not in sys.path:
        sys.path.insert(0, HELPER_DIR)
    results: Dict[str, int] = {}
    for name, _label in CHECKS:
        mod = __import__(name)
        try:
            results[name] = int(mod.main([]) or 0)
        except SystemExit as e:      # a lint that exits instead of returning
            results[name] = int(e.code or 0)
        except Exception as e:       # noqa: BLE001 — a crash IS a failure
            sys.stderr.write("ci_checks: %s crashed: %s: %s\n"
                             % (name, type(e).__name__, e))
            results[name] = 1
    return results


def main(argv: List[str] = None) -> int:
    results = run_all()
    width = max(len(n) for n, _ in CHECKS)
    for name, label in CHECKS:
        rc = results[name]
        print("ci_checks: %-*s %s  (%s)"
              % (width, name, "OK" if rc == 0 else "FAIL rc=%d" % rc,
                 label))
    failed = [n for n, rc in results.items() if rc != 0]
    if failed:
        print("ci_checks: FAILED: %s" % ", ".join(failed))
        return 1
    print("ci_checks: all %d lints green" % len(CHECKS))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
