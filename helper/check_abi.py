#!/usr/bin/env python
"""C-ABI drift lint (ISSUE 8 satellite).

Pins three invariants so the C-API surface cannot silently rot:

1. every ``LGBM_*`` entry point declared in ``cpp/lightgbm_tpu_c_api.h``
   appears in ``lightgbm_tpu/capi.py`` (a ctypes wrapper or an explicit
   mention — an exported symbol with no Python-side binding is drift);
2. every declared entry point that exists in the reference C API is
   accounted for in the canonical ``REFERENCE_C_API`` list below (a new
   export must be classified: reference-parity or an extension);
3. the parity fraction in ``PARITY.md`` equals the derived count
   ``|header ∩ REFERENCE_C_API| / |REFERENCE_C_API|`` — the number in the
   docs is computed, never hand-waved.

Run standalone (``python helper/check_abi.py``; exit code 1 on drift) or
through the tier-1 pin in ``tests/test_check_abi.py``.
"""
from __future__ import annotations

import os
import re
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(REPO, "cpp", "lightgbm_tpu_c_api.h")
CAPI = os.path.join(REPO, "lightgbm_tpu", "capi.py")
PARITY = os.path.join(REPO, "PARITY.md")

#: The reference's L9 entry-point list (c_api.h:48-808, SURVEY §L9: the
#: ABI every binding rides).  This is the denominator of the PARITY
#: fraction; names our header exports beyond it (e.g. the single-row
#: fast-path trio from newer reference versions) are extensions and do
#: not count toward it.
REFERENCE_C_API = (
    "LGBM_GetLastError",
    # dataset block
    "LGBM_DatasetCreateFromFile",
    "LGBM_DatasetCreateFromSampledColumn",
    "LGBM_DatasetCreateByReference",
    "LGBM_DatasetPushRows",
    "LGBM_DatasetPushRowsByCSR",
    "LGBM_DatasetCreateFromCSR",
    "LGBM_DatasetCreateFromCSC",
    "LGBM_DatasetCreateFromMat",
    "LGBM_DatasetCreateFromMats",
    "LGBM_DatasetGetSubset",
    "LGBM_DatasetSetFeatureNames",
    "LGBM_DatasetGetFeatureNames",
    "LGBM_DatasetFree",
    "LGBM_DatasetSaveBinary",
    "LGBM_DatasetDumpText",
    "LGBM_DatasetSetField",
    "LGBM_DatasetGetField",
    "LGBM_DatasetGetNumData",
    "LGBM_DatasetGetNumFeature",
    # booster block
    "LGBM_BoosterCreate",
    "LGBM_BoosterCreateFromModelfile",
    "LGBM_BoosterLoadModelFromString",
    "LGBM_BoosterFree",
    "LGBM_BoosterMerge",
    "LGBM_BoosterAddValidData",
    "LGBM_BoosterResetTrainingData",
    "LGBM_BoosterResetParameter",
    "LGBM_BoosterGetNumClasses",
    "LGBM_BoosterUpdateOneIter",
    "LGBM_BoosterRefit",
    "LGBM_BoosterUpdateOneIterCustom",
    "LGBM_BoosterRollbackOneIter",
    "LGBM_BoosterGetCurrentIteration",
    "LGBM_BoosterNumModelPerIteration",
    "LGBM_BoosterNumberOfTotalModel",
    "LGBM_BoosterGetEvalCounts",
    "LGBM_BoosterGetEvalNames",
    "LGBM_BoosterGetFeatureNames",
    "LGBM_BoosterGetNumFeature",
    "LGBM_BoosterGetEval",
    "LGBM_BoosterGetNumPredict",
    "LGBM_BoosterGetPredict",
    "LGBM_BoosterPredictForFile",
    "LGBM_BoosterCalcNumPredict",
    "LGBM_BoosterPredictForCSR",
    "LGBM_BoosterPredictForCSRSingleRow",
    "LGBM_BoosterPredictForCSC",
    "LGBM_BoosterPredictForMat",
    "LGBM_BoosterPredictForMatSingleRow",
    "LGBM_BoosterSaveModel",
    "LGBM_BoosterSaveModelToString",
    "LGBM_BoosterDumpModel",
    "LGBM_BoosterGetLeafValue",
    "LGBM_BoosterSetLeafValue",
    "LGBM_BoosterFeatureImportance",
    # network block
    "LGBM_NetworkInit",
    "LGBM_NetworkFree",
)

#: declaration matcher: return type at line start, then the symbol.
#: Mentions of LGBM_* inside comments/docstrings never match.
_DECL_RE = re.compile(r"^\s*(?:int|const\s+char\s*\*)\s+(LGBM_\w+)\s*\(",
                      re.MULTILINE)


def header_entry_points(header_path: str = HEADER) -> List[str]:
    with open(header_path) as fh:
        return sorted(set(_DECL_RE.findall(fh.read())))


def implemented_reference_points(header_path: str = HEADER) -> List[str]:
    ref = set(REFERENCE_C_API)
    return [s for s in header_entry_points(header_path) if s in ref]


def run(header_path: str = HEADER, capi_path: str = CAPI,
        parity_path: str = PARITY) -> List[str]:
    """Returns the list of drift problems (empty = clean)."""
    problems: List[str] = []
    exported = header_entry_points(header_path)
    if not exported:
        return ["no LGBM_* declarations found in %s" % header_path]
    with open(capi_path) as fh:
        capi_text = fh.read()
    for sym in exported:
        if not re.search(r"\b%s\b" % re.escape(sym), capi_text):
            problems.append(
                "%s is exported by the C header but has no wrapper or "
                "mention in capi.py" % sym)
    implemented = implemented_reference_points(header_path)
    claim = "%d/%d" % (len(implemented), len(REFERENCE_C_API))
    with open(parity_path) as fh:
        parity_text = fh.read()
    if claim not in parity_text:
        got = sorted(set(re.findall(r"\b(\d+/%d)\b" % len(REFERENCE_C_API),
                                    parity_text)))
        problems.append(
            "PARITY.md must state the derived C-API parity %r (header "
            "implements %d of the %d reference entry points); found %s"
            % (claim, len(implemented), len(REFERENCE_C_API),
               got or "no count"))
    return problems


def main(argv=None) -> int:
    problems = run()
    implemented = implemented_reference_points()
    print("check_abi: %d LGBM_* exports, %d/%d reference entry points"
          % (len(header_entry_points()), len(implemented),
             len(REFERENCE_C_API)))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_abi: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
