#!/usr/bin/env python
"""Fault-injection coverage lint (ISSUE 12 satellite).

`resilience.FAULT_TABLE` is the single registry of every injectable
fault, and docs/RESILIENCE.md is pinned row-for-row against it — but
nothing guaranteed a registered fault is actually EXERCISED.  A fault
mode nobody injects is worse than none: it documents a defense that has
never once been proven to fire.

This lint greps ``tests/test_*.py`` — plus the ``exp/*.py`` soak
drivers, whose fault POOLS are what the tier-1 quick-soak tests
(``test_quick_chaos_soak`` / ``test_quick_chaos_serve_soak`` / the
quality-soak pins) actually inject — for every FAULT_TABLE name: each
fault must appear in at least one of them as an injection spec, inside
a STRING LITERAL that arms it (``LGBM_TPU_FAULT=<name>...`` /
``"<name>:arg"`` / a fault-pool member).  A bare mention in a comment
or in code text does not count (only string literals are matched).

Run standalone (``python helper/check_fault_coverage.py``; exit 1 on a
gap) or through the tier-1 pin in ``tests/test_check_fault_coverage.py``
(which also pins the negative: a fabricated table entry IS reported).
"""
from __future__ import annotations

import glob
import io
import os
import re
import sys
import tokenize
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS_DIR = os.path.join(REPO, "tests")
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _string_literals(path: str) -> List[str]:
    """Every string literal in a python file (comments and code text
    excluded) — fault names must appear in an actual injection spec."""
    with open(path, "rb") as fh:
        src = fh.read()
    out: List[str] = []
    try:
        for tok in tokenize.tokenize(io.BytesIO(src).readline):
            if tok.type == tokenize.STRING:
                out.append(tok.string)
    except tokenize.TokenizeError:
        pass
    return out


def coverage(fault_names=None, tests_dir: str = TESTS_DIR
             ) -> Dict[str, List[str]]:
    """{fault_name: [files whose string literals arm it]}."""
    if fault_names is None:
        from lightgbm_tpu.runtime.resilience import FAULT_NAMES
        fault_names = FAULT_NAMES
    paths = sorted(glob.glob(os.path.join(tests_dir, "test_*.py")))
    exp_dir = os.path.join(os.path.dirname(os.path.abspath(tests_dir)),
                           "exp")
    paths += sorted(glob.glob(os.path.join(exp_dir, "*.py")))
    hits: Dict[str, List[str]] = {name: [] for name in fault_names}
    for path in paths:
        blob = "\n".join(_string_literals(path))
        base = os.path.basename(path)
        for name in fault_names:
            if re.search(r"\b%s\b" % re.escape(name), blob):
                hits[name].append(base)
    return hits


def run(fault_names=None, tests_dir: str = TESTS_DIR) -> List[str]:
    """Drift problems (empty = every registered fault is exercised)."""
    hits = coverage(fault_names, tests_dir)
    return ["fault %r is registered in resilience.FAULT_TABLE but no "
            "tests/test_*.py or exp/*.py string literal arms it — a "
            "defense that has never fired is not a defense" % name
            for name, files in sorted(hits.items()) if not files]


def main(argv=None) -> int:
    hits = coverage()
    problems = run()
    for name, files in sorted(hits.items()):
        print("%-20s %s" % (name, ", ".join(files) or "UNCOVERED"))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_fault_coverage: OK (%d faults, all exercised)"
              % len(hits))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
