#!/usr/bin/env python
"""Compile-ledger coverage lint (ISSUE 10 satellite).

The compile/retrace ledger (`lightgbm_tpu/runtime/xla_obs.py`) is only a
real instrument if EVERY jit entry point actually registers through it —
one raw ``jax.jit`` site and the zero-retrace pin can no longer prove
"nothing compiled".  This lint pins that property statically for every
``.py`` file under ``lightgbm_tpu/``:

1. no ``jax.jit(...)`` call or ``@jax.jit`` decoration — jitted programs
   go through ``xla_obs.jit(..., site=...)`` (which forwards to jax.jit
   with the trace marker attached);
2. no ``from jax import jit`` / ``from jax import ... jit ...`` — the
   alias would dodge rule 1;
3. a deliberate exception may be excused through the allowlist file
   (``helper/check_xla_sites_allowlist.txt``: ``<basename>:<regex>``
   lines) so it is visible and reviewed, never silent.

``runtime/xla_obs.py`` itself is exempt (it IS the seam).  Tokenization
strips comments and strings, so prose mentioning jax.jit never trips it
— same machinery as ``helper/check_syncs.py``.  Run standalone
(``python helper/check_xla_sites.py``; exit 1 on drift) or through the
tier-1 pin in ``tests/test_check_xla_sites.py`` (which also pins that
the lint CATCHES each violation class — drift-detection negatives).
"""
from __future__ import annotations

import os
import re
import sys
import tokenize
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "lightgbm_tpu")

ALLOWLIST_PATH = os.path.join(REPO, "helper",
                              "check_xla_sites_allowlist.txt")

#: the seam itself may (must) call jax.jit
EXEMPT_BASENAMES = ("xla_obs.py",)

_RULES: Tuple[Tuple[str, re.Pattern], ...] = (
    ("raw jax.jit", re.compile(r"\bjax\.jit\b")),
    ("jit imported from jax",
     re.compile(r"\bfrom jax import\b[^\n]*(?<![\w.])jit\b")),
)


def load_allowlist(path: str = ALLOWLIST_PATH
                   ) -> List[Tuple[str, re.Pattern]]:
    """``<basename>:<regex>`` entries; blank lines and # comments
    skipped."""
    entries: List[Tuple[str, re.Pattern]] = []
    try:
        with open(path) as fh:
            for raw in fh:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                fname, _, pattern = line.partition(":")
                entries.append((fname.strip(), re.compile(pattern.strip())))
    except OSError:
        pass
    return entries


def _allowed(fname: str, line: str,
             allowlist: List[Tuple[str, re.Pattern]]) -> bool:
    return any(f == fname and rx.search(line) for f, rx in allowlist)


def _code_lines(path: str) -> Dict[int, str]:
    """line number -> source with comments/strings removed (token-level,
    so docstrings naming jax.jit never match)."""
    drop = {tokenize.COMMENT, tokenize.STRING, tokenize.NL,
            tokenize.NEWLINE, tokenize.INDENT, tokenize.DEDENT,
            tokenize.ENCODING, tokenize.ENDMARKER}
    lines: Dict[int, List[str]] = {}
    with open(path, "rb") as fh:
        for tok in tokenize.tokenize(fh.readline):
            if tok.type in drop:
                continue
            lines.setdefault(tok.start[0], []).append(tok.string)
    out: Dict[int, str] = {}
    for no, parts in lines.items():
        joined = " ".join(parts)
        joined = re.sub(r"\s*\.\s*", ".", joined)
        joined = re.sub(r"\s*\(\s*", "(", joined)
        out[no] = joined
    return out


def scan_file(path: str,
              allowlist: List[Tuple[str, re.Pattern]]) -> List[str]:
    problems: List[str] = []
    fname = os.path.basename(path)
    if fname in EXEMPT_BASENAMES:
        return problems
    with open(path) as fh:
        raw_lines = fh.read().splitlines()
    for no, code in sorted(_code_lines(path).items()):
        raw = raw_lines[no - 1] if no <= len(raw_lines) else code
        for label, rx in _RULES:
            if rx.search(code):
                if _allowed(fname, raw, allowlist):
                    break
                problems.append(
                    "%s:%d: %s bypasses the compile ledger — use "
                    "xla_obs.jit(..., site=...): %s"
                    % (fname, no, label, raw.strip()))
                break
    return problems


def scan_files() -> List[str]:
    out: List[str] = []
    for root, _dirs, files in os.walk(PKG):
        for f in sorted(files):
            if f.endswith(".py"):
                out.append(os.path.join(root, f))
    return out


def run(files=None, allowlist_path: str = ALLOWLIST_PATH) -> List[str]:
    """Returns the list of drift problems (empty = clean)."""
    allowlist = load_allowlist(allowlist_path)
    problems: List[str] = []
    for path in (files if files is not None else scan_files()):
        problems.extend(scan_file(path, allowlist))
    return problems


def main(argv=None) -> int:
    files = scan_files()
    problems = run(files)
    print("check_xla_sites: scanned %d files, %d problem(s)"
          % (len(files), len(problems)))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_xla_sites: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
