#!/usr/bin/env python
"""Bench + sim trajectory collator (ISSUE 10 / ISSUE 11 satellites).

Five ``BENCH_r*.json`` driver artifacts sit at the repo root, yet the
round reports kept describing an "empty bench trajectory" — nothing
collated them.  This tool turns the committed artifacts into one
trajectory table (iters/sec, vs_baseline, per-section rows/sec) and
flags any round that regressed more than ``REGRESSION_THRESHOLD``
against the best PRIOR round measured at the same shape — cross-scale
comparisons (a 2M-row CPU round vs a 200k-row fallback round) are
meaningless and are never compared.

ISSUE 11 extends the same treatment to the production-sim artifacts
(``SIM_r*.json`` from exp/prod_sim.py): per-scenario p99 latency
(lower is better — a rise past the threshold flags) and capacity in
rows/sec/replica (higher is better — a drop flags), compared only
between rounds with the same replica count and duration.  Every SIM
artifact is schema-validated first (`validate_sim_artifact`); a
malformed sim run fails the collation loudly instead of collating as
zeros.

ISSUE 12 adds the quality-firewall artifacts (``CHAOS_QUALITY_r*.json``
from exp/chaos_quality.py): schema-validated like the sims (a rollback
that is not byte-verified, or a regressed generation reaching the
non-canary fleet, is an INVALID artifact), with the quarantine / gate /
rollback counts carried in the trajectory and the canary detection
window (batches-to-rollback, lower is better) under the same >10 %
regression-flag treatment.

ISSUE 16 adds the wire data-plane artifacts (``BENCH_WIRE_r*.json``
from exp/bench_wire.py): request rates per path (JSON/TCP vs binary
TCP vs binary UDS vs the compiled C client, higher is better) and the
binary/offered p99 tails (lower is better) under the same same-shape
>10 % treatment, behind a schema gate that makes an unverified
response or any JSON-vs-binary prediction mismatch an INVALID
artifact — throughput at wrong answers is not throughput.

ISSUE 20 extends the wire treatment to the shared-memory ring
transport: a ``binary_shm`` path series (req/s higher-better, p99
lower-better) plus the ``speedup_shm_over_uds`` trajectory column,
and — from artifact schema v2 on — a hard gate that the ``shm_plane``
section is present, byte-verified, and carries exactly zero prediction
mismatches (v1 artifacts from r16 stay valid without it).

Artifact shape (bench): the driver wraps each round's bench stdout as
``{"n": round, "rc": ..., "parsed": <bench JSON>, "tail": ...}``; when
``parsed`` is missing the last JSON-looking line of ``tail`` is tried.
SIM artifacts are written directly by exp/prod_sim.py (schema_version
stamped).

Run standalone (``python helper/bench_history.py``; exit 1 when a
regression is flagged or a SIM artifact is malformed) or through the
tier-1 pin in ``tests/test_bench_history.py`` (committed fixtures
collate clean; synthetic drops ARE flagged)."""
from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: a round is flagged when its value drops more than this fraction below
#: the best prior same-shape round
REGRESSION_THRESHOLD = 0.10

#: (series name, path into the parsed bench JSON, shape-key paths —
#: values compare only between rounds whose shape keys all match)
SERIES: Tuple[Tuple[str, Tuple[str, ...], Tuple[Tuple[str, ...], ...]], ...] = (
    ("iters_per_sec", ("value",),
     (("n_rows",), ("platform",))),
    ("vs_baseline", ("vs_baseline",),
     (("n_rows",), ("platform",))),
    ("predict_rows_per_sec", ("predict", "engine_rows_per_sec"),
     (("predict", "rows"), ("predict", "n_trees"))),
    ("serve_rows_per_sec", ("serve", "rows_per_sec"),
     (("serve", "n_trees"), ("serve", "clients"))),
    ("ingest_push_rows_per_sec", ("ingest", "dense_push_rows_per_sec"),
     (("ingest", "rows"),)),
    ("online_cycles_per_sec", ("online", "cycles_per_sec"),
     (("online", "rows"), ("online", "cycles"))),
)

#: like SERIES but LOWER is better — a RISE past the threshold flags.
#: dispatches_per_iter is BENCH_ATTRIB's device-program launch count per
#: iteration (ISSUE 13): the boost_window collapse of the dispatch loop
#: must not silently regress between rounds.  ISSUE 14 adds the rest of
#: the attrib decomposition (dispatch / device-wait / drain, reported in
#: ms): the per-piece trajectory across BENCH_r*/BENCH_WINDOW_r* rounds
#: is what tells the next hardware window WHICH piece moved.
SERIES_LOWER: Tuple[Tuple[str, Tuple[str, ...],
                          Tuple[Tuple[str, ...], ...]], ...] = (
    ("dispatches_per_iter",
     ("attrib", "per_iter", "dispatches_per_iter"),
     (("n_rows",), ("platform",))),
    ("attrib_dispatch_ms",
     ("attrib", "per_iter", "dispatch_s"),
     (("n_rows",), ("platform",))),
    ("attrib_device_wait_ms",
     ("attrib", "per_iter", "device_wait_s"),
     (("n_rows",), ("platform",))),
    ("attrib_drain_ms",
     ("attrib", "per_iter", "drain_s"),
     (("n_rows",), ("platform",))),
)

#: value transform per series (the attrib seconds render as ms)
_SERIES_SCALE: Dict[str, float] = {
    "attrib_dispatch_ms": 1000.0,
    "attrib_device_wait_ms": 1000.0,
    "attrib_drain_ms": 1000.0,
}


def _series_value(rec: Any, name: str, path: Tuple[str, ...]) -> Any:
    v = _get(rec, path)
    if isinstance(v, (int, float)) and name in _SERIES_SCALE:
        return round(v * _SERIES_SCALE[name], 3)
    return v


def _get(d: Any, path: Tuple[str, ...]) -> Optional[Any]:
    for k in path:
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d


def _parse_artifact(path: str) -> Optional[Dict[str, Any]]:
    """One round's parsed bench JSON, or None when the round left no
    usable record (red round: rc != 0 and nothing parsed)."""
    try:
        with open(path) as fh:
            art = json.load(fh)
    except (OSError, ValueError):
        return None
    parsed = art.get("parsed")
    if isinstance(parsed, dict) and "value" in parsed:
        out = dict(parsed)
        out["_round"] = int(art.get("n", 0))
        out["_rc"] = art.get("rc")
        return out
    # fall back: last {...} line of the captured tail
    for line in reversed((art.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                out = json.loads(line)
            except ValueError:
                continue
            if "value" in out:
                out["_round"] = int(art.get("n", 0))
                out["_rc"] = art.get("rc")
                return out
    return None


def load_rounds(repo: str = REPO) -> List[Dict[str, Any]]:
    """Every parseable BENCH_r*.json AND BENCH_WINDOW_r*.json, sorted by
    round number.  The window A/B artifacts carry the same parsed bench
    JSON (incl. the ``attrib`` section) at their own shape, so the
    same-shape guard keeps them from ever being compared against the
    full-scale rounds."""
    rounds = []
    for stem, pattern in (("BENCH_r*.json", r"BENCH_r(\d+)\.json$"),
                          ("BENCH_WINDOW_r*.json",
                           r"BENCH_WINDOW_r(\d+)\.json$")):
        for path in glob.glob(os.path.join(repo, stem)):
            m = re.search(pattern, path)
            if not m:
                continue
            rec = _parse_artifact(path)
            if rec is not None:
                if not rec.get("_round"):
                    rec["_round"] = int(m.group(1))
                rec["_file"] = os.path.basename(path)
                rounds.append(rec)
    return sorted(rounds, key=lambda r: (r["_round"], r["_file"]))


def trajectory(rounds: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per round: the SERIES values plus identifying shape."""
    rows = []
    for rec in rounds:
        row: Dict[str, Any] = {
            "round": rec["_round"], "file": rec.get("_file"),
            "n_rows": rec.get("n_rows"),
            "platform": rec.get("platform"),
            "sec_per_iter": rec.get("sec_per_iter"),
        }
        for name, path, _ in SERIES + SERIES_LOWER:
            v = _series_value(rec, name, path)
            if v is not None:
                row[name] = v
        rows.append(row)
    return rows


def regressions(rounds: List[Dict[str, Any]],
                threshold: float = REGRESSION_THRESHOLD
                ) -> List[Dict[str, Any]]:
    """Rounds whose series value moved > threshold the WRONG way vs the
    best PRIOR round at the same shape (below best for SERIES, above
    best for SERIES_LOWER)."""
    flags: List[Dict[str, Any]] = []
    for name, path, shape_paths, higher_better in \
            [s + (True,) for s in SERIES] + \
            [s + (False,) for s in SERIES_LOWER]:
        best: Dict[Tuple, Tuple[float, int]] = {}
        for rec in rounds:
            v = _series_value(rec, name, path)
            if not isinstance(v, (int, float)):
                continue
            shape = tuple(repr(_get(rec, sp)) for sp in shape_paths)
            prior = best.get(shape)
            if prior is not None and prior[0] > 0:
                worse = (v < prior[0] * (1.0 - threshold) if higher_better
                         else v > prior[0] * (1.0 + threshold))
                if worse:
                    flags.append({
                        "round": rec["_round"], "series": name,
                        "value": v, "best_prior": prior[0],
                        "best_prior_round": prior[1],
                        "drop_pct": round(abs(1.0 - v / prior[0]) * 100, 1),
                        "higher_is_better": higher_better,
                        "shape": shape,
                    })
            better = (prior is None or
                      (v > prior[0] if higher_better else v < prior[0]))
            if better:
                best[shape] = (float(v), rec["_round"])
    return sorted(flags, key=lambda f: (f["round"], f["series"]))


# ---------------------------------------------------------------------------
# quality-firewall artifacts (CHAOS_QUALITY_r*.json, ISSUE 12)
# ---------------------------------------------------------------------------

#: (series name, artifact-relative path, higher_is_better) — only the
#: canary detection window is treated as a performance series (how many
#: canary batches degradation took to catch; lower is better); the
#: quarantine/gate/rollback COUNTS are correctness evidence carried in
#: the trajectory rows and gated by the schema, not thresholds.
QUALITY_SERIES: Tuple[Tuple[str, Tuple[str, ...], bool], ...] = (
    ("canary_batches_to_rollback",
     ("phases", "canary", "canary_batches_to_rollback"), False),
)

_QUALITY_P1_REQUIRED = (
    ("quarantined_total", int),
    ("gate_rejections", int),
    ("published_generations", list),
    ("rejected_cycles", list),
    ("nonfinite_predictions", int),
    ("ok", bool),
)
_QUALITY_P2_REQUIRED = (
    ("rollback_count", int),
    ("canary_fraction", (int, float)),
    ("responses_bad_outside_canary", int),
    ("canary_events", dict),
    ("canary_batches", dict),
    ("ok", bool),
)


def validate_quality_artifact(rec: Any) -> List[str]:
    """Schema problems of one CHAOS_QUALITY artifact (empty = valid)."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return ["artifact is not a JSON object"]
    if not str(rec.get("artifact", "")).startswith("CHAOS_QUALITY_"):
        problems.append("artifact name %r does not start with "
                        "CHAOS_QUALITY_" % rec.get("artifact"))
    if not isinstance(rec.get("schema_version"), int):
        problems.append("schema_version missing or not an int")
    if not isinstance(rec.get("ok"), bool):
        problems.append("ok flag missing")
    phases = rec.get("phases")
    if not isinstance(phases, dict) or "ingest_gate" not in phases:
        problems.append("phases.ingest_gate missing")
        return problems
    p1 = phases["ingest_gate"]
    for key, typ in _QUALITY_P1_REQUIRED:
        if not isinstance(p1.get(key), typ):
            problems.append("ingest_gate: %s missing or wrong type" % key)
    p2 = phases.get("canary")
    if p2 is not None:
        for key, typ in _QUALITY_P2_REQUIRED:
            if not isinstance(p2.get(key), typ):
                problems.append("canary: %s missing or wrong type" % key)
        if p2.get("responses_bad_outside_canary"):
            problems.append("canary: responses_bad_outside_canary must be "
                            "0 — a regressed generation reached the "
                            "non-canary fleet")
        if p2.get("rollback_count") and \
                p2.get("rollback_byte_verified") is not True:
            problems.append("canary: rollback happened but was not "
                            "byte-verified against the restored "
                            "generation")
    return problems


def load_quality_rounds(repo: str = REPO):
    """(valid CHAOS_QUALITY rounds sorted, problems of invalid ones)."""
    rounds: List[Dict[str, Any]] = []
    problems: List[str] = []
    for path in glob.glob(os.path.join(repo, "CHAOS_QUALITY_r*.json")):
        m = re.search(r"CHAOS_QUALITY_r(\d+)\.json$", path)
        if not m:
            continue
        base = os.path.basename(path)
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, ValueError) as e:
            problems.append("%s: unreadable (%s)" % (base, e))
            continue
        bad = validate_quality_artifact(rec)
        if bad:
            problems.append("%s: %s" % (base, "; ".join(bad)))
            continue
        rec["_round"] = int(m.group(1))
        rec["_file"] = base
        rounds.append(rec)
    return sorted(rounds, key=lambda r: r["_round"]), problems


def quality_trajectory(rounds: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per round: the firewall's counts + the canary window."""
    rows = []
    for rec in rounds:
        p1 = rec["phases"]["ingest_gate"]
        p2 = rec["phases"].get("canary") or {}
        rows.append({
            "round": rec["_round"], "ok": rec.get("ok"),
            "quarantined_total": p1.get("quarantined_total"),
            "gate_rejections": p1.get("gate_rejections"),
            "published_generations": len(
                p1.get("published_generations") or []),
            "rollback_count": p2.get("rollback_count"),
            "canary_batches_to_rollback":
                p2.get("canary_batches_to_rollback"),
            "canary_fraction": p2.get("canary_fraction"),
        })
    return rows


def quality_regressions(rounds: List[Dict[str, Any]],
                        threshold: float = REGRESSION_THRESHOLD
                        ) -> List[Dict[str, Any]]:
    """Rounds whose QUALITY_SERIES moved > threshold the wrong way vs
    the best prior round at the same canary_fraction."""
    flags: List[Dict[str, Any]] = []
    for name, path, higher_better in QUALITY_SERIES:
        best: Dict[Tuple, Tuple[float, int]] = {}
        for rec in rounds:
            v = _get(rec, path)
            if not isinstance(v, (int, float)):
                continue
            shape = (repr(_get(rec, ("phases", "canary",
                                     "canary_fraction"))),)
            prior = best.get(shape)
            if prior is not None and prior[0] > 0:
                worse = (v < prior[0] * (1.0 - threshold) if higher_better
                         else v > prior[0] * (1.0 + threshold))
                if worse:
                    flags.append({
                        "round": rec["_round"], "series": name,
                        "value": v, "best_prior": prior[0],
                        "best_prior_round": prior[1],
                        "change_pct": round((v / prior[0] - 1.0) * 100, 1),
                        "shape": shape,
                    })
            better = (prior is None or
                      (v > prior[0] if higher_better else v < prior[0]))
            if better:
                best[shape] = (float(v), rec["_round"])
    return sorted(flags, key=lambda f: (f["round"], f["series"]))


# ---------------------------------------------------------------------------
# cold-start artifacts (BENCH_COLD_r*.json, ISSUE 15)
# ---------------------------------------------------------------------------

#: (series name, artifact-relative path, higher_is_better) — every
#: startup series is lower-is-better: time-to-ready and
#: join-to-first-response regressing past the threshold flags.
COLD_SERIES: Tuple[Tuple[str, Tuple[str, ...], bool], ...] = (
    ("coldstart_ready_manifest_s",
     ("modes", "manifest", "time_to_ready_s"), False),
    ("coldstart_first_response_manifest_s",
     ("modes", "manifest", "time_to_first_response_s"), False),
    ("join_to_first_response_s",
     ("replica_join", "join_to_first_response_s"), False),
    ("train_startup_overhead_warm_s",
     ("train", "warm", "startup_overhead_s"), False),
)

_COLD_MODE_REQUIRED = (
    ("time_to_ready_s", (int, float)),
    ("time_to_first_response_s", (int, float)),
    ("verified", bool),
    ("steady_retraces", int),
    ("pred_sha256", str),
)


def validate_coldstart_artifact(rec: Any) -> List[str]:
    """Schema problems of one BENCH_COLD artifact (empty = valid).  The
    hard gates ride the schema: unverified responses, steady-state
    retraces, or non-identical predictions across start modes make the
    artifact INVALID, not just slow."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return ["artifact is not a JSON object"]
    if not str(rec.get("artifact", "")).startswith("BENCH_COLD_"):
        problems.append("artifact name %r does not start with BENCH_COLD_"
                        % rec.get("artifact"))
    if not isinstance(rec.get("schema_version"), int):
        problems.append("schema_version missing or not an int")
    if not isinstance(rec.get("ok"), bool):
        problems.append("ok flag missing")
    modes = rec.get("modes")
    if not isinstance(modes, dict):
        problems.append("modes missing")
        return problems
    for mode in ("cold", "cache", "manifest"):
        sec = modes.get(mode)
        if not isinstance(sec, dict):
            problems.append("mode %r missing" % mode)
            continue
        for key, typ in _COLD_MODE_REQUIRED:
            if not isinstance(sec.get(key), typ):
                problems.append("mode %r: %s missing or wrong type"
                                % (mode, key))
        if sec.get("verified") is False:
            problems.append("mode %r: response was NOT byte-verified "
                            "against the offline predictor" % mode)
        if sec.get("steady_retraces"):
            problems.append("mode %r: steady-state retraces recorded "
                            "(the zero-retrace pin must hold under every "
                            "start mode)" % mode)
    if rec.get("predictions_identical") is not True:
        problems.append("predictions_identical must be true — start "
                        "modes changed the served bytes")
    train = rec.get("train")
    if not isinstance(train, dict):
        problems.append("train section missing")
    else:
        for mode in ("cold", "warm"):
            sec = train.get(mode)
            if not isinstance(sec, dict) or not isinstance(
                    sec.get("startup_overhead_s"), (int, float)):
                problems.append("train %r: startup_overhead_s missing"
                                % mode)
        if train.get("model_identical") is not True:
            problems.append("train: model_identical must be true — the "
                            "persistent cache changed the trained bits")
    join = rec.get("replica_join")
    if join is not None:
        if not isinstance(join.get("join_to_first_response_s"),
                          (int, float)):
            problems.append("replica_join: join_to_first_response_s "
                            "missing")
        if join.get("verified") is not True:
            problems.append("replica_join: first response was not "
                            "byte-verified")
    return problems


def load_coldstart_rounds(repo: str = REPO):
    """(valid BENCH_COLD rounds sorted, problems of invalid ones)."""
    rounds: List[Dict[str, Any]] = []
    problems: List[str] = []
    for path in glob.glob(os.path.join(repo, "BENCH_COLD_r*.json")):
        m = re.search(r"BENCH_COLD_r(\d+)\.json$", path)
        if not m:
            continue
        base = os.path.basename(path)
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, ValueError) as e:
            problems.append("%s: unreadable (%s)" % (base, e))
            continue
        bad = validate_coldstart_artifact(rec)
        if bad:
            problems.append("%s: %s" % (base, "; ".join(bad)))
            continue
        rec["_round"] = int(m.group(1))
        rec["_file"] = base
        rounds.append(rec)
    return sorted(rounds, key=lambda r: r["_round"]), problems


def coldstart_trajectory(rounds: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    rows = []
    for rec in rounds:
        row: Dict[str, Any] = {
            "round": rec["_round"], "platform": rec.get("platform"),
            "n_trees": rec.get("n_trees"), "ok": rec.get("ok"),
            "coldstart_ready_cold_s": _get(
                rec, ("modes", "cold", "time_to_ready_s")),
            "ready_speedup": _get(
                rec, ("speedup", "ready_cold_over_manifest")),
        }
        for name, path, _ in COLD_SERIES:
            v = _get(rec, path)
            if v is not None:
                row[name] = v
        rows.append(row)
    return rows


def coldstart_regressions(rounds: List[Dict[str, Any]],
                          threshold: float = REGRESSION_THRESHOLD
                          ) -> List[Dict[str, Any]]:
    """Rounds whose startup series ROSE > threshold vs the best prior
    round at the same (platform, n_trees) shape."""
    flags: List[Dict[str, Any]] = []
    for name, path, higher_better in COLD_SERIES:
        best: Dict[Tuple, Tuple[float, int]] = {}
        for rec in rounds:
            v = _get(rec, path)
            if not isinstance(v, (int, float)):
                continue
            shape = (repr(rec.get("platform")), repr(rec.get("n_trees")))
            prior = best.get(shape)
            if prior is not None and prior[0] > 0:
                worse = (v < prior[0] * (1.0 - threshold) if higher_better
                         else v > prior[0] * (1.0 + threshold))
                if worse:
                    flags.append({
                        "round": rec["_round"], "series": name,
                        "value": v, "best_prior": prior[0],
                        "best_prior_round": prior[1],
                        "change_pct": round((v / prior[0] - 1.0) * 100, 1),
                        "shape": shape,
                    })
            better = (prior is None or
                      (v > prior[0] if higher_better else v < prior[0]))
            if better:
                best[shape] = (float(v), rec["_round"])
    return sorted(flags, key=lambda f: (f["round"], f["series"]))


# ---------------------------------------------------------------------------
# wire data-plane artifacts (BENCH_WIRE_r*.json, ISSUE 16)
# ---------------------------------------------------------------------------

#: (series name, artifact-relative path, higher_is_better) — request
#: rates are higher-better, tail latency lower-better.  Shape key is
#: (platform, rows_per_request, conns, n_trees): a 1-row round must
#: never be compared against an 8-row round.
WIRE_SERIES: Tuple[Tuple[str, Tuple[str, ...], bool], ...] = (
    ("json_req_per_sec", ("paths", "json_tcp", "req_per_sec"), True),
    ("binary_tcp_req_per_sec",
     ("paths", "binary_tcp", "req_per_sec"), True),
    ("binary_uds_req_per_sec",
     ("paths", "binary_uds", "req_per_sec"), True),
    ("c_client_req_per_sec",
     ("paths", "c_client_uds", "req_per_sec"), True),
    ("fastconfig_req_per_sec",
     ("paths", "c_fastconfig", "req_per_sec"), True),
    # shared-memory ring transport (ISSUE 20, artifact schema v2) —
    # absent from pre-ring (v1) artifacts and silently skipped there
    ("shm_req_per_sec", ("paths", "binary_shm", "req_per_sec"), True),
    ("binary_uds_p99_ms", ("paths", "binary_uds", "p99_ms"), False),
    ("shm_p99_ms", ("paths", "binary_shm", "p99_ms"), False),
    ("offered_p99_ms", ("offered", "p99_ms"), False),
)

#: keys every socket-path section must carry; `verified` false or a
#: nonzero mismatch count is an INVALID artifact, not a slow one —
#: throughput at wrong answers is not throughput.
_WIRE_PATH_REQUIRED = (
    ("req_per_sec", (int, float)),
    ("verified", bool),
    ("prediction_mismatches", int),
)


def validate_wire_artifact(rec: Any) -> List[str]:
    """Schema problems of one BENCH_WIRE artifact (empty = valid)."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return ["artifact is not a JSON object"]
    if not str(rec.get("artifact", "")).startswith("BENCH_WIRE_"):
        problems.append("artifact name %r does not start with BENCH_WIRE_"
                        % rec.get("artifact"))
    if not isinstance(rec.get("schema_version"), int):
        problems.append("schema_version missing or not an int")
    if not isinstance(rec.get("ok"), bool):
        problems.append("ok flag missing")
    paths = rec.get("paths")
    if not isinstance(paths, dict) or not paths:
        problems.append("paths missing or empty")
        return problems
    sv = rec.get("schema_version")
    required_paths = ["json_tcp", "binary_tcp", "binary_uds"]
    if isinstance(sv, int) and sv >= 2:
        # the shm ring transport (ISSUE 20) is part of the contract
        # from schema v2 on; r16-era v1 artifacts stay valid without it
        required_paths.append("binary_shm")
    for pname in required_paths:
        sec = paths.get(pname)
        if not isinstance(sec, dict):
            problems.append("path %r missing" % pname)
            continue
        for key, typ in _WIRE_PATH_REQUIRED:
            if not isinstance(sec.get(key), typ):
                problems.append("path %r: %s missing or wrong type"
                                % (pname, key))
        if sec.get("verified") is False:
            problems.append("path %r: responses were NOT byte-verified "
                            "against the offline predictor" % pname)
        if sec.get("prediction_mismatches"):
            problems.append("path %r: %s prediction mismatch(es) — the "
                            "wire bytes disagreed with the offline "
                            "predictor" % (pname,
                                           sec["prediction_mismatches"]))
    if isinstance(sv, int) and sv >= 2:
        plane = rec.get("shm_plane")
        if not isinstance(plane, dict):
            problems.append("shm_plane section missing (required from "
                            "schema v2)")
        else:
            if plane.get("verified") is not True:
                problems.append("shm_plane: responses were NOT "
                                "byte-verified against the offline "
                                "predictor")
            if plane.get("prediction_mismatches") != 0:
                problems.append("shm_plane: prediction_mismatches must "
                                "be exactly 0, got %r"
                                % (plane.get("prediction_mismatches"),))
    for pname, sec in paths.items():
        if isinstance(sec, dict) and sec.get("prediction_mismatches"):
            if not any(pname in p for p in problems):
                problems.append("path %r: %s prediction mismatch(es)"
                                % (pname, sec["prediction_mismatches"]))
    offered = rec.get("offered")
    if not isinstance(offered, dict) or not isinstance(
            offered.get("offered_per_sec"), (int, float)):
        problems.append("offered section missing offered_per_sec")
    gates = rec.get("gates")
    if not isinstance(gates, dict):
        problems.append("gates section missing")
    else:
        for g, val in sorted(gates.items()):
            if val is not True:
                problems.append("gate %r did not hold" % g)
    return problems


def load_wire_rounds(repo: str = REPO):
    """(valid BENCH_WIRE rounds sorted, problems of invalid ones)."""
    rounds: List[Dict[str, Any]] = []
    problems: List[str] = []
    for path in glob.glob(os.path.join(repo, "BENCH_WIRE_r*.json")):
        m = re.search(r"BENCH_WIRE_r(\d+)\.json$", path)
        if not m:
            continue
        base = os.path.basename(path)
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, ValueError) as e:
            problems.append("%s: unreadable (%s)" % (base, e))
            continue
        bad = validate_wire_artifact(rec)
        if bad:
            problems.append("%s: %s" % (base, "; ".join(bad)))
            continue
        rec["_round"] = int(m.group(1))
        rec["_file"] = base
        rounds.append(rec)
    return sorted(rounds, key=lambda r: r["_round"]), problems


def _wire_shape(rec: Dict[str, Any]) -> Tuple:
    return (repr(rec.get("platform")),
            repr(rec.get("rows_per_request")),
            repr(rec.get("conns")),
            repr(_get(rec, ("model", "n_trees"))))


def wire_trajectory(rounds: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    rows = []
    for rec in rounds:
        row: Dict[str, Any] = {
            "round": rec["_round"], "platform": rec.get("platform"),
            "rows_per_request": rec.get("rows_per_request"),
            "conns": rec.get("conns"), "ok": rec.get("ok"),
            "speedup_binary_uds_over_json": _get(
                rec, ("speedup", "binary_uds_over_json")),
            "speedup_shm_over_uds": _get(
                rec, ("speedup", "shm_over_uds")),
            "offered_per_sec": _get(rec, ("offered", "offered_per_sec")),
        }
        for name, path, _ in WIRE_SERIES:
            v = _get(rec, path)
            if v is not None:
                row[name] = v
        rows.append(row)
    return rows


def wire_regressions(rounds: List[Dict[str, Any]],
                     threshold: float = REGRESSION_THRESHOLD
                     ) -> List[Dict[str, Any]]:
    """Rounds whose wire series moved > threshold the WRONG way vs the
    best prior round at the same shape."""
    flags: List[Dict[str, Any]] = []
    for name, path, higher_better in WIRE_SERIES:
        best: Dict[Tuple, Tuple[float, int]] = {}
        for rec in rounds:
            v = _get(rec, path)
            if not isinstance(v, (int, float)):
                continue
            shape = _wire_shape(rec)
            prior = best.get(shape)
            if prior is not None and prior[0] > 0:
                worse = (v < prior[0] * (1.0 - threshold) if higher_better
                         else v > prior[0] * (1.0 + threshold))
                if worse:
                    flags.append({
                        "round": rec["_round"], "series": name,
                        "value": v, "best_prior": prior[0],
                        "best_prior_round": prior[1],
                        "change_pct": round((v / prior[0] - 1.0) * 100, 1),
                        "shape": shape,
                    })
            better = (prior is None or
                      (v > prior[0] if higher_better else v < prior[0]))
            if better:
                best[shape] = (float(v), rec["_round"])
    return sorted(flags, key=lambda f: (f["round"], f["series"]))


# ---------------------------------------------------------------------------
# production-sim artifacts (SIM_r*.json, ISSUE 11)
# ---------------------------------------------------------------------------

#: (series name, scenario-relative path, higher_is_better)
SIM_SERIES: Tuple[Tuple[str, Tuple[str, ...], bool], ...] = (
    ("p99_latency_s", ("latency_s", "p99"), False),
    ("staleness_p50_s", ("staleness_s", "p50"), False),
    ("capacity_rows_per_sec_per_replica",
     ("capacity_rows_per_sec_per_replica",), True),
    # elastic-fleet efficiency (ISSUE 17): cost per verified outcome
    # and how fast added capacity clears an SLO breach — both lower-
    # better; absent from pre-fleet artifacts and silently skipped
    ("fleet_replica_s_per_1M_verified",
     ("fleet", "replica_seconds_per_million_verified"), False),
    ("fleet_scale_up_reaction_s",
     ("fleet", "scale_up_reaction_s_max"), False),
)

#: scenario keys every SIM artifact must carry with these types; the
#: schema gate that makes a malformed sim run fail loudly
_SIM_SCENARIO_REQUIRED = (
    ("objective", str),
    ("latency_s", dict),
    ("staleness_s", dict),
    ("capacity_rows_per_sec_per_replica", (int, float)),
    ("classes", dict),
    ("verification", dict),
    ("ok", bool),
)


def validate_sim_artifact(rec: Any) -> List[str]:
    """Schema problems of one SIM artifact dict (empty = valid)."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return ["artifact is not a JSON object"]
    if not str(rec.get("artifact", "")).startswith("SIM_"):
        problems.append("artifact name %r does not start with SIM_"
                        % rec.get("artifact"))
    if not isinstance(rec.get("schema_version"), int):
        problems.append("schema_version missing or not an int")
    if not isinstance(rec.get("replicas"), int) or rec.get("replicas", 0) < 1:
        problems.append("replicas missing or < 1")
    if not isinstance(rec.get("ok"), bool):
        problems.append("ok flag missing")
    scenarios = rec.get("scenarios")
    if not isinstance(scenarios, dict) or not scenarios:
        problems.append("scenarios missing or empty")
        return problems
    for name, sec in scenarios.items():
        if not isinstance(sec, dict):
            problems.append("scenario %r is not an object" % name)
            continue
        for key, typ in _SIM_SCENARIO_REQUIRED:
            if not isinstance(sec.get(key), typ):
                problems.append("scenario %r: %s missing or wrong type"
                                % (name, key))
        for hkey in ("latency_s", "staleness_s"):
            h = sec.get(hkey)
            if isinstance(h, dict):
                for q in ("p50", "p99", "count"):
                    if q not in h:
                        problems.append("scenario %r: %s.%s missing"
                                        % (name, hkey, q))
        for cname, cls in (sec.get("classes") or {}).items():
            if not isinstance(cls, dict):
                problems.append("scenario %r: class %r is not an object"
                                % (name, cname))
                continue
            for key in ("priority", "offered", "completed", "shed",
                        "shed_rate", "reasons"):
                if key not in cls:
                    problems.append("scenario %r: class %r misses %s"
                                    % (name, cname, key))
        # the fleet correctness gate (ISSUE 17): every completed
        # response must carry a verification verdict — a gap means the
        # byte-verifier silently skipped responses, which voids the
        # artifact's zero-mismatch claim
        vt, lc = sec.get("verified_total"), sec.get("loadgen_completed")
        if isinstance(vt, int) and isinstance(lc, int) and vt != lc:
            problems.append("scenario %r: verified_total %d != "
                            "loadgen_completed %d (unverified "
                            "completions)" % (name, vt, lc))
    return problems


def load_sim_rounds(repo: str = REPO):
    """(valid rounds sorted by number, problems of the invalid ones)."""
    rounds: List[Dict[str, Any]] = []
    problems: List[str] = []
    for path in glob.glob(os.path.join(repo, "SIM_r*.json")):
        m = re.search(r"SIM_r(\d+)\.json$", path)
        if not m:
            continue
        base = os.path.basename(path)
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, ValueError) as e:
            problems.append("%s: unreadable (%s)" % (base, e))
            continue
        bad = validate_sim_artifact(rec)
        if bad:
            problems.append("%s: %s" % (base, "; ".join(bad)))
            continue
        rec["_round"] = int(m.group(1))
        rec["_file"] = base
        rounds.append(rec)
    return sorted(rounds, key=lambda r: r["_round"]), problems


def sim_trajectory(rounds: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per (round, scenario) with the SIM_SERIES values."""
    rows = []
    for rec in rounds:
        for scen, sec in sorted(rec["scenarios"].items()):
            row: Dict[str, Any] = {
                "round": rec["_round"], "scenario": scen,
                "replicas": rec.get("replicas"),
                "duration_s": rec.get("duration_s"),
                "ok": sec.get("ok"),
            }
            for name, path, _ in SIM_SERIES:
                v = _get(sec, path)
                if v is not None:
                    row[name] = v
            rows.append(row)
    return rows


def sim_regressions(rounds: List[Dict[str, Any]],
                    threshold: float = REGRESSION_THRESHOLD
                    ) -> List[Dict[str, Any]]:
    """Rounds whose scenario series moved > threshold the WRONG way vs
    the best prior round at the same (scenario, replicas, duration)."""
    flags: List[Dict[str, Any]] = []
    for name, path, higher_better in SIM_SERIES:
        best: Dict[Tuple, Tuple[float, int]] = {}
        for rec in rounds:
            for scen, sec in sorted(rec["scenarios"].items()):
                v = _get(sec, path)
                if not isinstance(v, (int, float)):
                    continue
                shape = (scen, repr(rec.get("replicas")),
                         repr(rec.get("duration_s")))
                prior = best.get(shape)
                if prior is not None and prior[0] > 0:
                    worse = (v < prior[0] * (1.0 - threshold)
                             if higher_better
                             else v > prior[0] * (1.0 + threshold))
                    if worse:
                        flags.append({
                            "round": rec["_round"], "scenario": scen,
                            "series": name, "value": v,
                            "best_prior": prior[0],
                            "best_prior_round": prior[1],
                            "change_pct": round(
                                (v / prior[0] - 1.0) * 100, 1),
                            "shape": shape,
                        })
                better = (prior is None or
                          (v > prior[0] if higher_better else v < prior[0]))
                if better:
                    best[shape] = (float(v), rec["_round"])
    return sorted(flags, key=lambda f: (f["round"], f["scenario"],
                                        f["series"]))


def run(repo: str = REPO,
        threshold: float = REGRESSION_THRESHOLD) -> Dict[str, Any]:
    """Trajectory + all per-round regression flags.  The CHECK gates on
    the LATEST round only (``latest_regressions``): the tool runs after
    every round, so an old round's drop was that round's report — only a
    fresh drop should fail the current one.  SIM artifacts collate
    alongside with the same latest-round gating, plus a hard schema
    gate: an invalid SIM artifact always fails."""
    rounds = load_rounds(repo)
    flags = regressions(rounds, threshold)
    latest = rounds[-1]["_round"] if rounds else None
    sim_rounds, sim_problems = load_sim_rounds(repo)
    sim_flags = sim_regressions(sim_rounds, threshold)
    sim_latest = sim_rounds[-1]["_round"] if sim_rounds else None
    q_rounds, q_problems = load_quality_rounds(repo)
    q_flags = quality_regressions(q_rounds, threshold)
    q_latest = q_rounds[-1]["_round"] if q_rounds else None
    c_rounds, c_problems = load_coldstart_rounds(repo)
    c_flags = coldstart_regressions(c_rounds, threshold)
    c_latest = c_rounds[-1]["_round"] if c_rounds else None
    w_rounds, w_problems = load_wire_rounds(repo)
    w_flags = wire_regressions(w_rounds, threshold)
    w_latest = w_rounds[-1]["_round"] if w_rounds else None
    return {"rounds": len(rounds),
            "wire_rounds": len(w_rounds),
            "wire_latest_round": w_latest,
            "wire_trajectory": wire_trajectory(w_rounds),
            "wire_regressions": w_flags,
            "wire_latest_regressions": [f for f in w_flags
                                        if f["round"] == w_latest],
            "invalid_wire_artifacts": w_problems,
            "coldstart_rounds": len(c_rounds),
            "coldstart_latest_round": c_latest,
            "coldstart_trajectory": coldstart_trajectory(c_rounds),
            "coldstart_regressions": c_flags,
            "coldstart_latest_regressions": [f for f in c_flags
                                             if f["round"] == c_latest],
            "invalid_coldstart_artifacts": c_problems,
            "latest_round": latest,
            "trajectory": trajectory(rounds),
            "regressions": flags,
            "latest_regressions": [f for f in flags
                                   if f["round"] == latest],
            "sim_rounds": len(sim_rounds),
            "sim_latest_round": sim_latest,
            "sim_trajectory": sim_trajectory(sim_rounds),
            "sim_regressions": sim_flags,
            "sim_latest_regressions": [f for f in sim_flags
                                       if f["round"] == sim_latest],
            "invalid_sim_artifacts": sim_problems,
            "quality_rounds": len(q_rounds),
            "quality_latest_round": q_latest,
            "quality_trajectory": quality_trajectory(q_rounds),
            "quality_regressions": q_flags,
            "quality_latest_regressions": [f for f in q_flags
                                           if f["round"] == q_latest],
            "invalid_quality_artifacts": q_problems}


def main(argv=None) -> int:
    rep = run()
    cols = ["round", "n_rows", "platform", "iters_per_sec", "vs_baseline",
            "sec_per_iter"]
    print("bench_history: %d round(s) collated" % rep["rounds"])
    header = "  ".join("%-13s" % c for c in cols)
    print(header)
    for row in rep["trajectory"]:
        print("  ".join("%-13s" % (row.get(c, "-"),) for c in cols))
    for f in rep["regressions"]:
        kind = ("REGRESSION" if f["round"] == rep["latest_round"]
                else "historical regression")
        direction = ("below" if f.get("higher_is_better", True)
                     else "above")
        print("%s: round %d %s = %s is %.1f%% %s round %d's %s"
              % (kind, f["round"], f["series"], f["value"], f["drop_pct"],
                 direction, f["best_prior_round"], f["best_prior"]))
    print(json.dumps(rep["trajectory"][-1] if rep["trajectory"] else {}))
    if rep["sim_rounds"] or rep["invalid_sim_artifacts"]:
        print("bench_history: %d sim round(s) collated" % rep["sim_rounds"])
        sim_cols = ["round", "scenario", "p99_latency_s", "staleness_p50_s",
                    "capacity_rows_per_sec_per_replica", "ok"]
        print("  ".join("%-13s" % c for c in sim_cols))
        for row in rep["sim_trajectory"]:
            print("  ".join("%-13s" % (row.get(c, "-"),) for c in sim_cols))
        for f in rep["sim_regressions"]:
            kind = ("SIM REGRESSION"
                    if f["round"] == rep["sim_latest_round"]
                    else "historical sim regression")
            print("%s: round %d %s %s = %s moved %+.1f%% vs round %d's %s"
                  % (kind, f["round"], f["scenario"], f["series"],
                     f["value"], f["change_pct"], f["best_prior_round"],
                     f["best_prior"]))
        for p in rep["invalid_sim_artifacts"]:
            print("INVALID SIM ARTIFACT: %s" % p)
    if rep["quality_rounds"] or rep["invalid_quality_artifacts"]:
        print("bench_history: %d quality round(s) collated"
              % rep["quality_rounds"])
        q_cols = ["round", "quarantined_total", "gate_rejections",
                  "rollback_count", "canary_batches_to_rollback", "ok"]
        print("  ".join("%-13s" % c for c in q_cols))
        for row in rep["quality_trajectory"]:
            print("  ".join("%-13s" % (row.get(c, "-"),) for c in q_cols))
        for f in rep["quality_regressions"]:
            kind = ("QUALITY REGRESSION"
                    if f["round"] == rep["quality_latest_round"]
                    else "historical quality regression")
            print("%s: round %d %s = %s moved %+.1f%% vs round %d's %s"
                  % (kind, f["round"], f["series"], f["value"],
                     f["change_pct"], f["best_prior_round"],
                     f["best_prior"]))
        for p in rep["invalid_quality_artifacts"]:
            print("INVALID QUALITY ARTIFACT: %s" % p)
    if rep["coldstart_rounds"] or rep["invalid_coldstart_artifacts"]:
        print("bench_history: %d coldstart round(s) collated"
              % rep["coldstart_rounds"])
        c_cols = ["round", "platform", "coldstart_ready_manifest_s",
                  "join_to_first_response_s",
                  "train_startup_overhead_warm_s", "ok"]
        print("  ".join("%-13s" % c for c in c_cols))
        for row in rep["coldstart_trajectory"]:
            print("  ".join("%-13s" % (row.get(c, "-"),) for c in c_cols))
        for f in rep["coldstart_regressions"]:
            kind = ("COLDSTART REGRESSION"
                    if f["round"] == rep["coldstart_latest_round"]
                    else "historical coldstart regression")
            print("%s: round %d %s = %s moved %+.1f%% vs round %d's %s"
                  % (kind, f["round"], f["series"], f["value"],
                     f["change_pct"], f["best_prior_round"],
                     f["best_prior"]))
        for p in rep["invalid_coldstart_artifacts"]:
            print("INVALID COLDSTART ARTIFACT: %s" % p)
    if rep["wire_rounds"] or rep["invalid_wire_artifacts"]:
        print("bench_history: %d wire round(s) collated"
              % rep["wire_rounds"])
        w_cols = ["round", "json_req_per_sec", "binary_uds_req_per_sec",
                  "speedup_binary_uds_over_json", "offered_p99_ms", "ok"]
        print("  ".join("%-13s" % c for c in w_cols))
        for row in rep["wire_trajectory"]:
            print("  ".join("%-13s" % (row.get(c, "-"),) for c in w_cols))
        for f in rep["wire_regressions"]:
            kind = ("WIRE REGRESSION"
                    if f["round"] == rep["wire_latest_round"]
                    else "historical wire regression")
            print("%s: round %d %s = %s moved %+.1f%% vs round %d's %s"
                  % (kind, f["round"], f["series"], f["value"],
                     f["change_pct"], f["best_prior_round"],
                     f["best_prior"]))
        for p in rep["invalid_wire_artifacts"]:
            print("INVALID WIRE ARTIFACT: %s" % p)
    failed = bool(rep["latest_regressions"]
                  or rep["sim_latest_regressions"]
                  or rep["invalid_sim_artifacts"]
                  or rep["quality_latest_regressions"]
                  or rep["invalid_quality_artifacts"]
                  or rep["coldstart_latest_regressions"]
                  or rep["invalid_coldstart_artifacts"]
                  or rep["wire_latest_regressions"]
                  or rep["invalid_wire_artifacts"])
    if not failed:
        print("bench_history: OK (latest round has no >%.0f%% regression)"
              % (REGRESSION_THRESHOLD * 100))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
