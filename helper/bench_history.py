#!/usr/bin/env python
"""Bench-trajectory collator (ISSUE 10 satellite).

Five ``BENCH_r*.json`` driver artifacts sit at the repo root, yet the
round reports kept describing an "empty bench trajectory" — nothing
collated them.  This tool turns the committed artifacts into one
trajectory table (iters/sec, vs_baseline, per-section rows/sec) and
flags any round that regressed more than ``REGRESSION_THRESHOLD``
against the best PRIOR round measured at the same shape — cross-scale
comparisons (a 2M-row CPU round vs a 200k-row fallback round) are
meaningless and are never compared.

Artifact shape: the driver wraps each round's bench stdout as
``{"n": round, "rc": ..., "parsed": <bench JSON>, "tail": ...}``; when
``parsed`` is missing the last JSON-looking line of ``tail`` is tried.

Run standalone (``python helper/bench_history.py``; exit 1 when a
regression is flagged) or through the tier-1 pin in
``tests/test_bench_history.py`` (committed r01–r05 fixtures collate
clean; synthetic drops ARE flagged)."""
from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: a round is flagged when its value drops more than this fraction below
#: the best prior same-shape round
REGRESSION_THRESHOLD = 0.10

#: (series name, path into the parsed bench JSON, shape-key paths —
#: values compare only between rounds whose shape keys all match)
SERIES: Tuple[Tuple[str, Tuple[str, ...], Tuple[Tuple[str, ...], ...]], ...] = (
    ("iters_per_sec", ("value",),
     (("n_rows",), ("platform",))),
    ("vs_baseline", ("vs_baseline",),
     (("n_rows",), ("platform",))),
    ("predict_rows_per_sec", ("predict", "engine_rows_per_sec"),
     (("predict", "rows"), ("predict", "n_trees"))),
    ("serve_rows_per_sec", ("serve", "rows_per_sec"),
     (("serve", "n_trees"), ("serve", "clients"))),
    ("ingest_push_rows_per_sec", ("ingest", "dense_push_rows_per_sec"),
     (("ingest", "rows"),)),
    ("online_cycles_per_sec", ("online", "cycles_per_sec"),
     (("online", "rows"), ("online", "cycles"))),
)


def _get(d: Any, path: Tuple[str, ...]) -> Optional[Any]:
    for k in path:
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d


def _parse_artifact(path: str) -> Optional[Dict[str, Any]]:
    """One round's parsed bench JSON, or None when the round left no
    usable record (red round: rc != 0 and nothing parsed)."""
    try:
        with open(path) as fh:
            art = json.load(fh)
    except (OSError, ValueError):
        return None
    parsed = art.get("parsed")
    if isinstance(parsed, dict) and "value" in parsed:
        out = dict(parsed)
        out["_round"] = int(art.get("n", 0))
        out["_rc"] = art.get("rc")
        return out
    # fall back: last {...} line of the captured tail
    for line in reversed((art.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                out = json.loads(line)
            except ValueError:
                continue
            if "value" in out:
                out["_round"] = int(art.get("n", 0))
                out["_rc"] = art.get("rc")
                return out
    return None


def load_rounds(repo: str = REPO) -> List[Dict[str, Any]]:
    """Every parseable BENCH_r*.json, sorted by round number."""
    rounds = []
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rec = _parse_artifact(path)
        if rec is not None:
            rec.setdefault("_round", int(m.group(1)))
            rec["_file"] = os.path.basename(path)
            rounds.append(rec)
    return sorted(rounds, key=lambda r: r["_round"])


def trajectory(rounds: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per round: the SERIES values plus identifying shape."""
    rows = []
    for rec in rounds:
        row: Dict[str, Any] = {
            "round": rec["_round"], "file": rec.get("_file"),
            "n_rows": rec.get("n_rows"),
            "platform": rec.get("platform"),
            "sec_per_iter": rec.get("sec_per_iter"),
        }
        for name, path, _ in SERIES:
            v = _get(rec, path)
            if v is not None:
                row[name] = v
        rows.append(row)
    return rows


def regressions(rounds: List[Dict[str, Any]],
                threshold: float = REGRESSION_THRESHOLD
                ) -> List[Dict[str, Any]]:
    """Rounds whose series value dropped > threshold below the best
    PRIOR round at the same shape."""
    flags: List[Dict[str, Any]] = []
    for name, path, shape_paths in SERIES:
        best: Dict[Tuple, Tuple[float, int]] = {}
        for rec in rounds:
            v = _get(rec, path)
            if not isinstance(v, (int, float)):
                continue
            shape = tuple(repr(_get(rec, sp)) for sp in shape_paths)
            prior = best.get(shape)
            if prior is not None and v < prior[0] * (1.0 - threshold):
                flags.append({
                    "round": rec["_round"], "series": name,
                    "value": v, "best_prior": prior[0],
                    "best_prior_round": prior[1],
                    "drop_pct": round((1.0 - v / prior[0]) * 100, 1),
                    "shape": shape,
                })
            if prior is None or v > prior[0]:
                best[shape] = (float(v), rec["_round"])
    return sorted(flags, key=lambda f: (f["round"], f["series"]))


def run(repo: str = REPO,
        threshold: float = REGRESSION_THRESHOLD) -> Dict[str, Any]:
    """Trajectory + all per-round regression flags.  The CHECK gates on
    the LATEST round only (``latest_regressions``): the tool runs after
    every round, so an old round's drop was that round's report — only a
    fresh drop should fail the current one."""
    rounds = load_rounds(repo)
    flags = regressions(rounds, threshold)
    latest = rounds[-1]["_round"] if rounds else None
    return {"rounds": len(rounds),
            "latest_round": latest,
            "trajectory": trajectory(rounds),
            "regressions": flags,
            "latest_regressions": [f for f in flags
                                   if f["round"] == latest]}


def main(argv=None) -> int:
    rep = run()
    cols = ["round", "n_rows", "platform", "iters_per_sec", "vs_baseline",
            "sec_per_iter"]
    print("bench_history: %d round(s) collated" % rep["rounds"])
    header = "  ".join("%-13s" % c for c in cols)
    print(header)
    for row in rep["trajectory"]:
        print("  ".join("%-13s" % (row.get(c, "-"),) for c in cols))
    for f in rep["regressions"]:
        kind = ("REGRESSION" if f["round"] == rep["latest_round"]
                else "historical regression")
        print("%s: round %d %s = %s is %.1f%% below round %d's %s"
              % (kind, f["round"], f["series"], f["value"], f["drop_pct"],
                 f["best_prior_round"], f["best_prior"]))
    print(json.dumps(rep["trajectory"][-1] if rep["trajectory"] else {}))
    if not rep["latest_regressions"]:
        print("bench_history: OK (latest round has no >%.0f%% regression)"
              % (REGRESSION_THRESHOLD * 100))
    return 1 if rep["latest_regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
