#!/usr/bin/env python
"""Metric-coverage lint (ISSUE 14 satellite — lint #5 in ci_checks).

`telemetry.METRIC_TABLE` is the single registry of every product metric
and docs/OBSERVABILITY.md is pinned row-for-row against it — but
nothing guaranteed a declared family is actually ARMED: a metric nobody
instruments is worse than none (it documents an observable that has
never once been observed — the fault-coverage-lint argument, applied to
the instrument panel).

This lint scans ``lightgbm_tpu/**/*.py`` and ``exp/*.py`` (plus
``bench.py``, which arms the bench-only reads) for every METRIC_TABLE
family name appearing as an INSTRUMENT CONSTRUCTOR call —
``counter("name")`` / ``gauge("name")`` / ``histogram("name")`` with
the name as a string literal — so the table's own declaration block
(where every name trivially appears as a dict key) can never arm
anything.  Every family must have at least one call site.

Run standalone (``python helper/check_metric_coverage.py``; exit 1 on a
gap) or through ``helper/ci_checks.py``; ``tests/test_ci_checks.py``
pins the committed tree green AND the drift negative (a fabricated
table entry IS reported).
"""
from __future__ import annotations

import glob
import os
import re
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _scan_paths(repo: str) -> List[str]:
    paths = sorted(glob.glob(os.path.join(repo, "lightgbm_tpu", "**",
                                          "*.py"), recursive=True))
    paths += sorted(glob.glob(os.path.join(repo, "exp", "*.py")))
    bench = os.path.join(repo, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    return paths


def _call_site_re(name: str) -> "re.Pattern":
    """An arming call site: an instrument constructor taking the family
    name as a literal — `counter("x")`, `REGISTRY.histogram('x')`,
    `telemetry.gauge("x")` all match; a bare mention (a dict key, a
    docstring, a scraped read of a snapshot) does not."""
    return re.compile(
        r"\b(?:counter|gauge|histogram)\(\s*[rbu]*['\"]%s['\"]"
        % re.escape(name))


def coverage(table: Optional[Dict] = None,
             repo: str = REPO) -> Dict[str, List[str]]:
    """{family name: [files with an arming call site]}."""
    if table is None:
        from lightgbm_tpu.runtime.telemetry import METRIC_TABLE
        table = METRIC_TABLE
    blobs = []
    for path in _scan_paths(repo):
        try:
            with open(path, encoding="utf-8") as fh:
                blobs.append((os.path.relpath(path, repo), fh.read()))
        except OSError:
            continue
    hits: Dict[str, List[str]] = {}
    for name in table:
        pat = _call_site_re(name)
        hits[name] = [rel for rel, blob in blobs if pat.search(blob)]
    return hits


def run(table: Optional[Dict] = None, repo: str = REPO) -> List[str]:
    """Drift problems (empty = every declared family is armed)."""
    hits = coverage(table, repo)
    return ["metric %r is declared in METRIC_TABLE but no instrument "
            "call site in lightgbm_tpu/ or exp/ arms it — an observable "
            "nobody ever observes is dead weight in the catalog" % name
            for name, files in sorted(hits.items()) if not files]


def main(argv=None) -> int:
    hits = coverage()
    problems = run()
    for name, files in sorted(hits.items()):
        print("%-40s %s" % (name, ", ".join(files[:3]) or "UNARMED"))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_metric_coverage: OK (%d families, all armed)"
              % len(hits))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
