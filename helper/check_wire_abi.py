#!/usr/bin/env python
"""Wire-frame ABI drift lint (ISSUE 16 satellite, lint #6).

The binary serving data plane has two independent definitions of the
40-byte frame header: the Python ``HEADER_FIELDS`` tuple in
``lightgbm_tpu/runtime/wire.py`` (the servers and the Python client)
and the ``WIRE_FRAME_FIELDS:`` token line + packed
``LGBMWireFrameHeader`` struct in ``cpp/lightgbm_tpu_c_api.h`` (the
compiled reference client and any external caller).  A field added,
renamed, reordered or re-typed on one side only would produce frames
the other side misparses — silently, because both sides still "work"
against themselves.  This lint pins the two layouts field-for-field:

1. the header's ``WIRE_FRAME_FIELDS:`` tokens (``name:fmt`` pairs, in
   order) must equal the Python ``HEADER_FIELDS`` tuple exactly —
   names AND struct(3) format codes, compared tokenized so comment
   re-wrapping cannot fake agreement;
2. the Python layout must pack to exactly the size the header's
   ``LGBM_WIRE_HEADER_SIZE`` macro promises (40);
3. ``make -C cpp wire_client`` must succeed — the compiled client is
   part of the contract, and a header edit that breaks its build is
   drift even if the token line still matches.

The shared-memory ring transport (ISSUE 20) adds a second pinned
layout: the 40-byte segment header both sides map at offset 0.  The
same three checks run against the header's ``WIRE_RING_FIELDS:``
token line + ``LGBMWireRingHeader`` struct vs the Python
``RING_HEADER_FIELDS`` tuple in ``runtime/shm_ring.py`` and the
``LGBM_WIRE_RING_HEADER_SIZE`` macro.

Run standalone (``python helper/check_wire_abi.py``; exit 1 on drift)
or through ``helper/ci_checks.py``; ``tests/test_ci_checks.py`` pins a
negative (a doctored header MUST fail) so the comparator cannot rot
into a no-op.  Set ``CHECK_WIRE_ABI_NO_BUILD=1`` to skip the compile
step (used by the pure-text negative tests).
"""
from __future__ import annotations

import os
import re
import struct
import subprocess
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(REPO, "cpp", "lightgbm_tpu_c_api.h")
WIRE = os.path.join(REPO, "lightgbm_tpu", "runtime", "wire.py")
SHM = os.path.join(REPO, "lightgbm_tpu", "runtime", "shm_ring.py")

#: the C header's canonical token lines: "WIRE_FRAME_FIELDS:" (frame
#: header) / "WIRE_RING_FIELDS:" (shm segment header) then
#: whitespace-separated name:fmt tokens, possibly wrapped over several
#: comment lines (continuation lines start with "*").
_C_BLOCK_RE = re.compile(
    r"WIRE_FRAME_FIELDS:\s*((?:[\w]+:[\w]+[ \t]*|\n\s*\*\s*)+)")
_C_RING_RE = re.compile(
    r"WIRE_RING_FIELDS:\s*((?:[\w]+:[\w]+[ \t]*|\n\s*\*\s*)+)")
_TOKEN_RE = re.compile(r"(\w+):(\w+)")

#: Python side: the ("name", "fmt") pairs of the HEADER_FIELDS /
#: RING_HEADER_FIELDS tuples.  Matched textually (not imported) so the
#: lint needs no jax and sees exactly what is committed.
_PY_PAIR_RE = re.compile(r"\(\s*\"(\w+)\"\s*,\s*\"(\w+)\"\s*\)")
_SIZE_MACRO_RE = re.compile(r"#define\s+LGBM_WIRE_HEADER_SIZE\s*\((\d+)\)")
_RING_SIZE_MACRO_RE = re.compile(
    r"#define\s+LGBM_WIRE_RING_HEADER_SIZE\s*\((\d+)\)")


def c_header_fields(header_text: str) -> List[Tuple[str, str]]:
    m = _C_BLOCK_RE.search(header_text)
    if not m:
        return []
    return _TOKEN_RE.findall(m.group(1))


def c_ring_fields(header_text: str) -> List[Tuple[str, str]]:
    m = _C_RING_RE.search(header_text)
    if not m:
        return []
    return _TOKEN_RE.findall(m.group(1))


def py_header_fields(wire_text: str) -> List[Tuple[str, str]]:
    m = re.search(r"HEADER_FIELDS[^=]*=\s*\((.*?)\n\)", wire_text,
                  re.DOTALL)
    if not m:
        return []
    return _PY_PAIR_RE.findall(m.group(1))


def py_ring_fields(shm_text: str) -> List[Tuple[str, str]]:
    m = re.search(r"RING_HEADER_FIELDS[^=]*=\s*\((.*?)\n\)", shm_text,
                  re.DOTALL)
    if not m:
        return []
    return _PY_PAIR_RE.findall(m.group(1))


def _compare(c_fields: List[Tuple[str, str]],
             py_fields: List[Tuple[str, str]], what: str,
             py_home: str, problems: List[str]) -> None:
    if c_fields and py_fields and c_fields != py_fields:
        for i in range(max(len(c_fields), len(py_fields))):
            c = c_fields[i] if i < len(c_fields) else None
            p = py_fields[i] if i < len(py_fields) else None
            if c != p:
                problems.append(
                    "%s field %d drifted: C header says %s, %s says %s"
                    % (what, i, c and "%s:%s" % c, py_home,
                       p and "%s:%s" % p))


def _check_size(py_fields: List[Tuple[str, str]], header_text: str,
                macro_re, macro_name: str, tuple_name: str,
                problems: List[str]) -> None:
    fmt = "<" + "".join(f for _n, f in py_fields)
    try:
        size = struct.calcsize(fmt)
    except struct.error as e:
        size = -1
        problems.append("%s does not form a valid struct format (%s): %s"
                        % (tuple_name, fmt, e))
    m = macro_re.search(header_text)
    if not m:
        problems.append("%s macro missing from the C header" % macro_name)
    elif size >= 0 and int(m.group(1)) != size:
        problems.append(
            "%s is %s but the Python layout packs to %d bytes"
            % (macro_name, m.group(1), size))


def run(header_text: str = None, wire_text: str = None,
        build: bool = True, shm_text: str = None) -> List[str]:
    """Returns the list of drift problems (empty = clean)."""
    problems: List[str] = []
    if header_text is None:
        with open(HEADER) as fh:
            header_text = fh.read()
    if wire_text is None:
        with open(WIRE) as fh:
            wire_text = fh.read()
    if shm_text is None:
        with open(SHM) as fh:
            shm_text = fh.read()

    c_fields = c_header_fields(header_text)
    py_fields = py_header_fields(wire_text)
    if not c_fields:
        problems.append("no WIRE_FRAME_FIELDS token line found in the C "
                        "header")
    if not py_fields:
        problems.append("no HEADER_FIELDS tuple found in runtime/wire.py")
    _compare(c_fields, py_fields, "frame header", "wire.py", problems)
    if py_fields:
        _check_size(py_fields, header_text, _SIZE_MACRO_RE,
                    "LGBM_WIRE_HEADER_SIZE", "HEADER_FIELDS", problems)

    # the shm segment header (ISSUE 20) — same three checks against
    # runtime/shm_ring.py's RING_HEADER_FIELDS
    c_ring = c_ring_fields(header_text)
    py_ring = py_ring_fields(shm_text)
    if not c_ring:
        problems.append("no WIRE_RING_FIELDS token line found in the C "
                        "header")
    if not py_ring:
        problems.append("no RING_HEADER_FIELDS tuple found in "
                        "runtime/shm_ring.py")
    _compare(c_ring, py_ring, "ring header", "shm_ring.py", problems)
    if py_ring:
        _check_size(py_ring, header_text, _RING_SIZE_MACRO_RE,
                    "LGBM_WIRE_RING_HEADER_SIZE", "RING_HEADER_FIELDS",
                    problems)

    if build and not os.environ.get("CHECK_WIRE_ABI_NO_BUILD"):
        proc = subprocess.run(
            ["make", "-C", os.path.join(REPO, "cpp"), "wire_client"],
            capture_output=True, text=True)
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            problems.append("make -C cpp wire_client failed (rc=%d): %s"
                            % (proc.returncode,
                               "; ".join(tail[-3:]) or "no output"))
    return problems


def main(argv=None) -> int:
    problems = run()
    header_text = open(HEADER).read()
    fields = c_header_fields(header_text)
    ring = c_ring_fields(header_text)
    print("check_wire_abi: %d frame header fields + %d ring header "
          "fields, C header vs wire.py/shm_ring.py"
          % (len(fields), len(ring)))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_wire_abi: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
