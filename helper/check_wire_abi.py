#!/usr/bin/env python
"""Wire-frame ABI drift lint (ISSUE 16 satellite, lint #6).

The binary serving data plane has two independent definitions of the
40-byte frame header: the Python ``HEADER_FIELDS`` tuple in
``lightgbm_tpu/runtime/wire.py`` (the servers and the Python client)
and the ``WIRE_FRAME_FIELDS:`` token line + packed
``LGBMWireFrameHeader`` struct in ``cpp/lightgbm_tpu_c_api.h`` (the
compiled reference client and any external caller).  A field added,
renamed, reordered or re-typed on one side only would produce frames
the other side misparses — silently, because both sides still "work"
against themselves.  This lint pins the two layouts field-for-field:

1. the header's ``WIRE_FRAME_FIELDS:`` tokens (``name:fmt`` pairs, in
   order) must equal the Python ``HEADER_FIELDS`` tuple exactly —
   names AND struct(3) format codes, compared tokenized so comment
   re-wrapping cannot fake agreement;
2. the Python layout must pack to exactly the size the header's
   ``LGBM_WIRE_HEADER_SIZE`` macro promises (40);
3. ``make -C cpp wire_client`` must succeed — the compiled client is
   part of the contract, and a header edit that breaks its build is
   drift even if the token line still matches.

Run standalone (``python helper/check_wire_abi.py``; exit 1 on drift)
or through ``helper/ci_checks.py``; ``tests/test_ci_checks.py`` pins a
negative (a doctored header MUST fail) so the comparator cannot rot
into a no-op.  Set ``CHECK_WIRE_ABI_NO_BUILD=1`` to skip the compile
step (used by the pure-text negative tests).
"""
from __future__ import annotations

import os
import re
import struct
import subprocess
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(REPO, "cpp", "lightgbm_tpu_c_api.h")
WIRE = os.path.join(REPO, "lightgbm_tpu", "runtime", "wire.py")

#: the C header's canonical token line: "WIRE_FRAME_FIELDS:" then
#: whitespace-separated name:fmt tokens, possibly wrapped over several
#: comment lines (continuation lines start with "*").
_C_BLOCK_RE = re.compile(
    r"WIRE_FRAME_FIELDS:\s*((?:[\w]+:[\w]+[ \t]*|\n\s*\*\s*)+)")
_TOKEN_RE = re.compile(r"(\w+):(\w+)")

#: Python side: the ("name", "fmt") pairs of the HEADER_FIELDS tuple.
#: Matched textually (not imported) so the lint needs no jax and sees
#: exactly what is committed.
_PY_PAIR_RE = re.compile(r"\(\s*\"(\w+)\"\s*,\s*\"(\w+)\"\s*\)")
_SIZE_MACRO_RE = re.compile(r"#define\s+LGBM_WIRE_HEADER_SIZE\s*\((\d+)\)")


def c_header_fields(header_text: str) -> List[Tuple[str, str]]:
    m = _C_BLOCK_RE.search(header_text)
    if not m:
        return []
    return _TOKEN_RE.findall(m.group(1))


def py_header_fields(wire_text: str) -> List[Tuple[str, str]]:
    m = re.search(r"HEADER_FIELDS[^=]*=\s*\((.*?)\n\)", wire_text,
                  re.DOTALL)
    if not m:
        return []
    return _PY_PAIR_RE.findall(m.group(1))


def run(header_text: str = None, wire_text: str = None,
        build: bool = True) -> List[str]:
    """Returns the list of drift problems (empty = clean)."""
    problems: List[str] = []
    if header_text is None:
        with open(HEADER) as fh:
            header_text = fh.read()
    if wire_text is None:
        with open(WIRE) as fh:
            wire_text = fh.read()

    c_fields = c_header_fields(header_text)
    py_fields = py_header_fields(wire_text)
    if not c_fields:
        problems.append("no WIRE_FRAME_FIELDS token line found in the C "
                        "header")
    if not py_fields:
        problems.append("no HEADER_FIELDS tuple found in runtime/wire.py")
    if c_fields and py_fields and c_fields != py_fields:
        for i in range(max(len(c_fields), len(py_fields))):
            c = c_fields[i] if i < len(c_fields) else None
            p = py_fields[i] if i < len(py_fields) else None
            if c != p:
                problems.append(
                    "frame header field %d drifted: C header says %s, "
                    "wire.py says %s" % (i, c and "%s:%s" % c,
                                         p and "%s:%s" % p))

    if py_fields:
        fmt = "<" + "".join(f for _n, f in py_fields)
        try:
            size = struct.calcsize(fmt)
        except struct.error as e:
            size = -1
            problems.append("HEADER_FIELDS does not form a valid struct "
                            "format (%s): %s" % (fmt, e))
        m = _SIZE_MACRO_RE.search(header_text)
        if not m:
            problems.append("LGBM_WIRE_HEADER_SIZE macro missing from the "
                            "C header")
        elif size >= 0 and int(m.group(1)) != size:
            problems.append(
                "LGBM_WIRE_HEADER_SIZE is %s but the Python layout packs "
                "to %d bytes" % (m.group(1), size))

    if build and not os.environ.get("CHECK_WIRE_ABI_NO_BUILD"):
        proc = subprocess.run(
            ["make", "-C", os.path.join(REPO, "cpp"), "wire_client"],
            capture_output=True, text=True)
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            problems.append("make -C cpp wire_client failed (rc=%d): %s"
                            % (proc.returncode,
                               "; ".join(tail[-3:]) or "no output"))
    return problems


def main(argv=None) -> int:
    problems = run()
    fields = c_header_fields(open(HEADER).read())
    print("check_wire_abi: %d frame header fields, C header vs wire.py"
          % len(fields))
    for p in problems:
        print("DRIFT: %s" % p)
    if not problems:
        print("check_wire_abi: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
