#!/usr/bin/env python
"""BENCH_WIRE: wire-speed serving data-plane harness (ISSUE 16).

Measures the same model served through every front end the runtime
carries, at equal byte-verified correctness — every counted response is
compared against the offline predictor for its reported generation and
serving path, so a fast-but-wrong plane can never produce a valid
artifact:

* **json_tcp** — the JSON-lines TCP front end (PR 7): one utf-8 JSON
  object per request/response line.  The baseline the tentpole is
  measured against.
* **binary_tcp / binary_uds** — the ISSUE 16 length-prefixed binary
  frame protocol (runtime/wire.py) over TCP and over a Unix-domain
  socket: 40-byte header + raw float32 payload, CRC-checked, gathered
  zero-copy into per-connection receive buckets and admitted without a
  float64 conversion (`submit_view`).
* **c_client_uds / c_fastconfig** — the compiled reference client
  (cpp/wire_client.c) driving the UDS socket protocol and the
  in-process `LGBM_BoosterPredictForMatSingleRowFast` ABI: proof from
  OUTSIDE Python, with client-side CRC + byte verification.
* **binary_shm** — the ISSUE 20 shared-memory ring transport
  (runtime/shm_ring.py): same frames, written straight into a mapped
  SPSC ring pair instead of a socket, closed-loop from Python at the
  same shape as the socket paths.
* **offered** — an open-throttle overload phase against a deliberately
  small admission queue: clients hammer without honoring backoff so
  the OFFERED rate (completed + rejected frames) exceeds the
  acceptance bar while every rejection stays a machine-readable frame;
  the p99 of the requests that did complete is recorded under that
  load.
* **shm_plane** — the ring transport's own claim, proved from OUTSIDE
  Python by the compiled client: single-row single-connection UDS
  closed loop vs the pipelined shm ring at the same shape, with a
  post-warmup syscall window (every doorbell syscall the client makes
  is counted; the spin-hot steady state must make ZERO) and the
  server-side ring allocation ledger (the rx path admits mapped views
  and must never allocate; the tx scratch is sized once per session,
  never per request).
* **predictor** — the flattened branchless device engine measured
  directly (f64 vs f32 response surfaces vs int8-quantized leaves)
  with the quantization error vs the f64 host path, feeding the
  `LEAF_QUANT_VALIDATED` expiry row in docs/PERFORMANCE.md.

Gates (all must hold or the artifact is INVALID):
  binary_uds_ge_5x_json   best binary UDS req/s >= 5x JSON req/s
  offered_ge_10k          offered phase >= 10k req/s on this host
  c_client_green          compiled client rc 0, zero mismatches
  zero_mismatches         no sampled response anywhere disagreed
  shm_ge_2x_uds           pipelined shm ring >= 2x the UDS socket
                          closed loop at the same single-row shape
  shm_zero_syscalls       zero transport syscalls over the client's
                          post-warmup window (spin-hot steady state)
  shm_zero_allocs         zero per-request ring allocations server-side
                          (no rx buffers ever; tx scratch <= 1/session)

Usage:
    python exp/bench_wire.py [--quick] [--out OUT.json]
    python exp/bench_wire.py --artifact BENCH_WIRE_r16.json

Env knobs: BENCH_WIRE_TREES/LEAVES/FEAT (model shape, default
40/31/28 — small enough that the plane, not predict, is measured),
BENCH_WIRE_SECS (per-phase seconds, default 5), BENCH_WIRE_CONNS
(closed-loop connections, default 8), BENCH_WIRE_ROWS (rows per
request, default 512 — bulk-scoring frames where zero-copy pays),
BENCH_WIRE_SHM_SPIN (doorbell spin budget for the shm_plane phase,
seconds, default 2.0 — long enough that the steady state never
sleeps, which is what the zero-syscall window proves).

The artifact is schema-validated (`helper.bench_history.
validate_wire_artifact`) before it is written and collated by
`helper/bench_history.py` under the same >10% same-shape regression
flags as every other bench family."""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.basic import Booster                     # noqa: E402
from lightgbm_tpu.runtime import shm_ring                  # noqa: E402
from lightgbm_tpu.runtime import wire                      # noqa: E402
from lightgbm_tpu.runtime.serving import (ServingRuntime,  # noqa: E402
                                          ServingServer)

#: v2 adds the shm transport (binary_shm path + shm_plane section with
#: its three gates); helper/bench_history.py requires them from v2 on
SCHEMA_VERSION = 2


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def _summary(lat_s: List[float], completed: int, rejected: int,
             mismatches: int, elapsed: float, rows: int) -> Dict[str, Any]:
    lat = sorted(lat_s)
    return {
        "req_per_sec": round(completed / elapsed, 1),
        "rows_per_sec": round(completed * rows / elapsed, 1),
        "p50_ms": round(_pct(lat, 0.50) * 1e3, 4),
        "p99_ms": round(_pct(lat, 0.99) * 1e3, 4),
        "completed": completed, "rejected": rejected,
        "verified": True,                # every response was compared...
        "prediction_mismatches": mismatches,   # ...and THIS many failed
    }


class _Refs:
    """Offline predictions per serving path, float32 response surface.
    Device-served responses must match the device engine's f64 surface
    downcast; host-degraded responses the host engine's."""

    def __init__(self, booster: Booster, probes: np.ndarray):
        X = np.asarray(probes, np.float64)
        self.device = np.asarray(
            booster.predict(X, device=True), np.float64).reshape(
                len(probes), -1).astype(np.float32)
        self.host = np.asarray(
            booster.predict(X), np.float64).reshape(
                len(probes), -1).astype(np.float32)
        self.n_out = self.device.shape[1]

    def check(self, start: int, vals: np.ndarray, served_by: str) -> int:
        """Number of mismatched rows for a window starting at probe
        row `start` (wrapping)."""
        ref = self.device if served_by == "device" else self.host
        n = len(vals)
        idx = (start + np.arange(n)) % len(ref)
        want = ref[idx]
        got = np.asarray(vals, np.float32).reshape(n, -1)
        return int(np.sum(~np.all(got == want, axis=1)))


def _closed_loop(n_conns: int, secs: float, make_worker) -> Dict[str, Any]:
    """Run n_conns worker threads for secs; each worker returns
    (completed, rejected, mismatches, [latencies])."""
    stop = threading.Event()
    out: List[Optional[tuple]] = [None] * n_conns
    ths = []
    for i in range(n_conns):
        th = threading.Thread(target=make_worker(i, stop, out), daemon=True)
        ths.append(th)
    t0 = time.monotonic()
    for th in ths:
        th.start()
    time.sleep(secs)
    stop.set()
    for th in ths:
        th.join(timeout=30)
    elapsed = time.monotonic() - t0
    completed = rejected = mismatches = 0
    lat: List[float] = []
    for rec in out:
        if rec is None:
            continue
        completed += rec[0]
        rejected += rec[1]
        mismatches += rec[2]
        lat.extend(rec[3])
    return {"completed": completed, "rejected": rejected,
            "mismatches": mismatches, "lat": lat, "elapsed": elapsed}


def bench_json_tcp(port: int, probes: np.ndarray, refs: _Refs,
                   conns: int, rows: int, secs: float) -> Dict[str, Any]:
    # requests pre-encoded outside the loop: the measured path is the
    # server's decode/encode + the response parse, not client dumps()
    reqs = []
    for s in range(0, len(probes) - rows + 1, rows):
        reqs.append((s, (json.dumps(
            {"features": probes[s:s + rows].tolist()}) + "\n").encode()))

    def make_worker(i, stop, out):
        def work():
            comp = rej = mis = 0
            lat: List[float] = []
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=30) as sk:
                sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                f = sk.makefile("rwb")
                k = i % len(reqs)
                while not stop.is_set():
                    start, payload = reqs[k]
                    k = (k + 1) % len(reqs)
                    t0 = time.monotonic()
                    f.write(payload)
                    f.flush()
                    resp = json.loads(f.readline())
                    lat_s = time.monotonic() - t0
                    if "values" in resp:
                        comp += 1
                        lat.append(lat_s)
                        mis += refs.check(
                            start, np.asarray(resp["values"], np.float32),
                            resp.get("served_by", "device"))
                    else:
                        rej += 1
            out[i] = (comp, rej, mis, lat)
        return work

    r = _closed_loop(conns, secs, make_worker)
    return _summary(r["lat"], r["completed"], r["rejected"],
                    r["mismatches"], r["elapsed"], rows)


def bench_binary(address, probes: np.ndarray, refs: _Refs, conns: int,
                 rows: int, secs: float) -> Dict[str, Any]:
    frames = []
    for s in range(0, len(probes) - rows + 1, rows):
        frames.append((s, wire.pack_request(probes[s:s + rows])))

    def make_worker(i, stop, out):
        def work():
            comp = rej = mis = 0
            lat: List[float] = []
            with wire.WireClient(address, timeout=30) as c:
                k = i % len(frames)
                while not stop.is_set():
                    start, frame = frames[k]
                    k = (k + 1) % len(frames)
                    t0 = time.monotonic()
                    c._sock.sendall(frame)
                    got = wire.read_frame(c._rfile)
                    lat_s = time.monotonic() - t0
                    resp = wire.unpack_response(*got)
                    if "values" in resp:
                        comp += 1
                        lat.append(lat_s)
                        mis += refs.check(start, resp["values"],
                                          resp["served_by"])
                    else:
                        rej += 1
            out[i] = (comp, rej, mis, lat)
        return work

    r = _closed_loop(conns, secs, make_worker)
    return _summary(r["lat"], r["completed"], r["rejected"],
                    r["mismatches"], r["elapsed"], rows)


def bench_shm(uds_path: str, probes: np.ndarray, refs: _Refs, conns: int,
              rows: int, secs: float) -> Dict[str, Any]:
    """The ring transport at the socket paths' shape: conns ShmClient
    sessions, one request in flight each, byte-verified like the rest
    of the four-way."""
    windows = [(s, np.ascontiguousarray(probes[s:s + rows]))
               for s in range(0, len(probes) - rows + 1, rows)]

    def make_worker(i, stop, out):
        def work():
            comp = rej = mis = 0
            lat: List[float] = []
            with shm_ring.ShmClient(uds_path, timeout=30) as c:
                k = i % len(windows)
                while not stop.is_set():
                    start, X = windows[k]
                    k = (k + 1) % len(windows)
                    t0 = time.monotonic()
                    resp = c.request_once(X)
                    lat_s = time.monotonic() - t0
                    if "values" in resp:
                        comp += 1
                        lat.append(lat_s)
                        mis += refs.check(start, resp["values"],
                                          resp["served_by"])
                    else:
                        rej += 1
                        time.sleep(float(resp.get("retry_after_s")
                                         or 0.001))
            out[i] = (comp, rej, mis, lat)
        return work

    r = _closed_loop(conns, secs, make_worker)
    return _summary(r["lat"], r["completed"], r["rejected"],
                    r["mismatches"], r["elapsed"], rows)


def bench_shm_plane(uds_path: str, workdir: str, probes: np.ndarray,
                    refs: _Refs, secs: float) -> Dict[str, Any]:
    """The tentpole's own numbers, from OUTSIDE Python: the compiled
    client drives single-row requests over (a) a single-connection UDS
    closed loop and (b) the pipelined shm ring, same frames and byte
    verification both ways.  Both sides' doorbells get a spin budget
    longer than any steady-state gap so the post-warmup window counts
    ZERO transport syscalls; the server-side ring ledger delta proves
    the rx path allocated nothing and the tx scratch was sized at most
    once per session."""
    client = os.path.join(REPO, "cpp", "wire_client")
    probes_f = os.path.join(workdir, "probes.f32")
    expect_f = os.path.join(workdir, "expect.f32")
    if not os.path.exists(probes_f):
        probes.astype(np.float32).tofile(probes_f)
        refs.device.tofile(expect_f)
    common = ["--probes", probes_f, "--expect", expect_f,
              "--expect-gen", "0", "--ncols", str(probes.shape[1]),
              "--n-out", str(refs.n_out), "--rows", "1",
              "--secs", str(secs)]

    def _one(cmd):
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=secs * 6 + 60)
        try:
            parsed = json.loads(proc.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            parsed = {"error":
                      (proc.stderr or proc.stdout).strip()[-300:]}
        parsed["rc"] = proc.returncode
        return parsed

    u = _one([client, "uds", uds_path, "--conns", "1"] + common)
    stats0 = shm_ring.stats_snapshot()
    spin = os.environ.get("BENCH_WIRE_SHM_SPIN", "2.0")
    old_spin = os.environ.get("LGBM_TPU_SHM_SPIN_S")
    os.environ["LGBM_TPU_SHM_SPIN_S"] = spin   # server-side sessions
    try:
        s = _one([client, "shm", uds_path, "--pipeline", "64",
                  "--spin", spin, "--warmup",
                  str(max(1.0, secs * 0.4))] + common)
    finally:
        if old_spin is None:
            os.environ.pop("LGBM_TPU_SHM_SPIN_S", None)
        else:
            os.environ["LGBM_TPU_SHM_SPIN_S"] = old_spin
    # let the session thread notice peer exit and tear down before the
    # ledger is read (its doorbell spin can outlive the client by the
    # spin budget)
    deadline = time.monotonic() + float(spin) + 5.0
    while time.monotonic() < deadline:
        now = shm_ring.stats_snapshot()
        if now["closed"] + now["reclaimed"] + now["torn"] >= \
                stats0["closed"] + stats0["reclaimed"] + stats0["torn"] \
                + 1:
            break
        time.sleep(0.1)
    stats1 = shm_ring.stats_snapshot()
    delta = {k: stats1[k] - stats0[k] for k in stats1}

    u_rps = float(u.get("req_per_sec") or 0.0)
    s_rps = float(s.get("req_per_sec") or 0.0)
    win_completed = int(s.get("win_completed") or 0)
    win_syscalls = int(s.get("win_syscalls") or 0)
    verified = bool(
        u.get("rc") == 0 and s.get("rc") == 0
        and (u.get("verify_checked") or 0) > 0
        and (s.get("verify_checked") or 0) > 0)
    mismatches = int(u.get("verify_mismatch") or 0) \
        + int(s.get("verify_mismatch") or 0)
    return {
        "uds_single_conn": u, "shm": s,
        "rows_per_request": 1, "pipeline": 64,
        "speedup_shm_over_uds": round(s_rps / u_rps, 2) if u_rps else 0.0,
        "win_completed": win_completed,
        "win_syscalls": win_syscalls,
        "syscalls_per_request": round(win_syscalls / win_completed, 6)
        if win_completed else None,
        "ring_stats_delta": delta,
        "verified": verified,
        "prediction_mismatches": mismatches,
    }


def bench_offered(uds_path: str, workdir: str, probes: np.ndarray,
                  refs: _Refs, conns: int,
                  secs: float) -> Dict[str, Any]:
    """Open-throttle single-row overload via the compiled client's
    `--no-backoff` mode: clients deliberately ignore retry_after_s
    hints so the OFFERED rate (completed + rejected frames) probes the
    admission plane's ceiling; every rejection must still arrive as a
    machine-readable frame (a torn/garbled one would break the client's
    frame loop and count as an error).  p50/p99 are over the requests
    that completed under that load, still byte-verified."""
    probes_f = os.path.join(workdir, "probes.f32")
    expect_f = os.path.join(workdir, "expect.f32")
    if not os.path.exists(probes_f):
        probes.astype(np.float32).tofile(probes_f)
        refs.device.tofile(expect_f)
    client = os.path.join(REPO, "cpp", "wire_client")
    if not os.path.exists(client):
        subprocess.run(["make", "-C", os.path.join(REPO, "cpp"),
                        "wire_client"], capture_output=True)
    cmd = [client, "uds", uds_path,
           "--probes", probes_f, "--expect", expect_f, "--expect-gen",
           "0", "--ncols", str(probes.shape[1]), "--n-out",
           str(refs.n_out), "--rows", "1", "--conns", str(conns),
           "--secs", str(secs), "--no-backoff"]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=secs * 4 + 60)
    if proc.returncode != 0:
        return {"rc": proc.returncode, "offered_per_sec": 0.0,
                "verified": False, "prediction_mismatches": 0,
                "error": (proc.stderr or proc.stdout).strip()[-300:]}
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    return {
        "offered_per_sec": round(
            (r["completed"] + r["rejected"]) / r["elapsed_s"], 1),
        "completed_per_sec": round(r["completed"] / r["elapsed_s"], 1),
        "completed": r["completed"], "rejected": r["rejected"],
        "errors": r["errors"],
        "p50_ms": r["p50_ms"], "p99_ms": r["p99_ms"],
        "conns": conns, "client": "wire_client --no-backoff",
        "verified": r["verify_checked"] > 0 and r["errors"] == 0,
        "prediction_mismatches": r["verify_mismatch"],
    }


def bench_c_client(uds_path: str, workdir: str, probes: np.ndarray,
                   refs: _Refs, model_file: str, conns: int, rows: int,
                   secs: float) -> Dict[str, Any]:
    """The compiled reference client: socket mode (byte-verifying
    against --expect) and the in-process FastConfig single-row ABI."""
    cpp = os.path.join(REPO, "cpp")
    build = subprocess.run(["make", "-C", cpp, "wire_client"],
                           capture_output=True, text=True)
    out: Dict[str, Any] = {"build_rc": build.returncode}
    if build.returncode != 0:
        out["error"] = (build.stderr or build.stdout).strip()[-500:]
        return out
    probes_f = os.path.join(workdir, "probes.f32")
    expect_f = os.path.join(workdir, "expect.f32")
    probes.astype(np.float32).tofile(probes_f)
    refs.device.tofile(expect_f)
    cmd = [os.path.join(cpp, "wire_client"), "uds", uds_path,
           "--probes", probes_f, "--expect", expect_f, "--expect-gen",
           "0", "--ncols", str(probes.shape[1]), "--n-out",
           str(refs.n_out), "--rows", str(rows), "--conns", str(conns),
           "--secs", str(secs)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=secs * 4 + 60)
    out["socket_rc"] = proc.returncode
    try:
        sock = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        out["error"] = (proc.stderr or proc.stdout).strip()[-500:]
        return out
    out.update({
        "req_per_sec": sock["req_per_sec"],
        "rows_per_sec": sock["rows_per_sec"],
        "p50_ms": sock["p50_ms"], "p99_ms": sock["p99_ms"],
        "completed": sock["completed"], "rejected": sock["rejected"],
        "errors": sock["errors"],
        "verified": sock["verify_checked"] > 0,
        "verify_checked": sock["verify_checked"],
        "prediction_mismatches": sock["verify_mismatch"],
    })
    # FastConfig mode needs the dependency-free base lib
    lib = os.path.join(cpp, "lib_lightgbm_tpu.so")
    if not os.path.exists(lib):
        libb = subprocess.run(["make", "-C", cpp, "lib_lightgbm_tpu.so"],
                              capture_output=True, text=True)
        if libb.returncode != 0:
            out["fastconfig"] = {"skipped": "lib build failed"}
            return out
    fcmd = [os.path.join(cpp, "wire_client"), "fastconfig", lib,
            model_file, "--probes", probes_f, "--ncols",
            str(probes.shape[1]), "--secs", str(max(2, int(secs // 2)))]
    fproc = subprocess.run(fcmd, capture_output=True, text=True,
                           timeout=secs * 4 + 60)
    try:
        fc = json.loads(fproc.stdout.strip().splitlines()[-1])
        out["fastconfig"] = {
            "rc": fproc.returncode,
            "req_per_sec": fc["req_per_sec"], "calls": fc["calls"],
            "errors": fc["errors"], "checksum": fc["checksum"],
            # single-row host ABI: correctness rides the checksum and
            # the ABI's own byte-parity pins (tests/test_capi.py)
            "verified": fproc.returncode == 0 and fc["errors"] == 0,
            "prediction_mismatches": 0 if fproc.returncode == 0 else 1,
        }
    except (ValueError, IndexError):
        out["fastconfig"] = {"rc": fproc.returncode, "error":
                             (fproc.stderr or fproc.stdout).strip()[-300:]}
    return out


def bench_predictor(booster: Booster, probes: np.ndarray,
                    secs: float) -> Dict[str, Any]:
    """The flattened branchless engine, engine-level: f64 vs f32
    response surfaces vs int8-quantized leaves, plus the quantization
    error that the LEAF_QUANT_VALIDATED expiry row gates on."""
    from lightgbm_tpu.models.device_predictor import DevicePredictor
    X = np.asarray(probes, np.float64)
    host = np.asarray(booster.predict(X, raw_score=True),
                      np.float64).reshape(len(X), -1)
    out: Dict[str, Any] = {}
    for label, kw, out_dtype in (
            ("f64", {}, np.float64),
            ("f32", {}, np.float32),
            ("int8", {"leaf_quant": "int8"}, np.float32)):
        dp = DevicePredictor(booster._model, **kw)
        vals = dp.predict_raw(X, out_dtype=out_dtype)    # warm the trace
        n = 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < secs:
            dp.predict_raw(X, out_dtype=out_dtype)
            n += 1
        dt = time.monotonic() - t0
        out["%s_rows_per_sec" % label] = round(n * len(X) / dt, 1)
        if label == "int8":
            err = float(np.max(np.abs(
                np.asarray(vals, np.float64).reshape(host.shape) - host)))
            out["int8_max_abs_err_vs_f64_host"] = round(err, 8)
    from lightgbm_tpu.models import device_predictor as dpr
    out["leaf_quant_validated_flag"] = bool(dpr.LEAF_QUANT_VALIDATED)
    return out


def run(quick: bool = False, workdir: Optional[str] = None
        ) -> Dict[str, Any]:
    import tempfile
    import bench
    workdir = workdir or tempfile.mkdtemp(prefix="bench_wire_")
    # default profile: a serving-shape ensemble small enough that the
    # DATA PLANE, not the predict dispatch, is what the closed loop
    # measures (at 100x63 predict is ~6.5us/row on this class of host
    # and both planes converge on it; the plane difference is then
    # invisible no matter how fast the wire is).  BENCH_WIRE_TREES=100
    # BENCH_WIRE_LEAVES=63 reshapes it for predict-bound runs.
    n_trees = int(os.environ.get("BENCH_WIRE_TREES", 40))
    leaves = int(os.environ.get("BENCH_WIRE_LEAVES", 31))
    feat = int(os.environ.get("BENCH_WIRE_FEAT", 28))
    secs = float(os.environ.get("BENCH_WIRE_SECS", 2 if quick else 5))
    conns = int(os.environ.get("BENCH_WIRE_CONNS", 4 if quick else 8))
    rows = int(os.environ.get("BENCH_WIRE_ROWS", 512))
    if quick:
        n_trees, leaves = min(n_trees, 20), min(leaves, 15)

    model = bench.synth_serving_model(n_trees, leaves, feat, seed=7)
    model_str = model.save_model_to_string()
    model_file = os.path.join(workdir, "model.txt")
    model.save_model(model_file)
    booster = Booster(model_str=model_str)
    rng = np.random.default_rng(0)
    probes = rng.standard_normal((max(256, rows * 2), feat)
                                 ).astype(np.float32)
    refs = _Refs(booster, probes)

    rec: Dict[str, Any] = {
        "artifact": None, "schema_version": SCHEMA_VERSION,
        "platform": str(os.environ.get("JAX_PLATFORMS") or "default"),
        "model": {"n_trees": n_trees, "num_leaves": leaves,
                  "n_feat": feat, "n_out": refs.n_out},
        "rows_per_request": rows, "conns": conns,
        "phase_secs": secs, "paths": {},
    }

    def _wait_ready(rt, timeout=120.0):
        t0 = time.monotonic()
        while not rt._ready.is_set():
            if time.monotonic() - t0 > timeout:
                raise RuntimeError("runtime never became ready")
            time.sleep(0.05)

    # ---- closed-loop serving phases: one runtime, three front ends
    uds_path = os.path.join(workdir, "wire.sock")
    with ServingRuntime(model_str=model_str, batch_window_s=0.0,
                        max_queue=2048, max_batch_rows=4096,
                        response_dtype="float32") as rt:
        _wait_ready(rt)
        jsrv = ServingServer(rt)
        tsrv = wire.WireTCPServer(rt, port=0)
        usrv = wire.WireUnixServer(rt, path=uds_path)
        for s in (jsrv, tsrv, usrv):
            threading.Thread(target=s.serve_forever, daemon=True).start()
        try:
            print("bench_wire: json_tcp...", file=sys.stderr, flush=True)
            rec["paths"]["json_tcp"] = bench_json_tcp(
                jsrv.port, probes, refs, conns, rows, secs)
            print("bench_wire: binary_tcp...", file=sys.stderr, flush=True)
            rec["paths"]["binary_tcp"] = bench_binary(
                ("127.0.0.1", tsrv.port), probes, refs, conns, rows, secs)
            print("bench_wire: binary_uds...", file=sys.stderr, flush=True)
            rec["paths"]["binary_uds"] = bench_binary(
                uds_path, probes, refs, conns, rows, secs)
            print("bench_wire: c_client...", file=sys.stderr, flush=True)
            rec["paths"]["c_client_uds"] = bench_c_client(
                uds_path, workdir, probes, refs, model_file, conns, rows,
                secs)
            print("bench_wire: binary_shm...", file=sys.stderr,
                  flush=True)
            rec["paths"]["binary_shm"] = bench_shm(
                uds_path, probes, refs, conns, rows, secs)
            print("bench_wire: shm_plane...", file=sys.stderr, flush=True)
            rec["shm_plane"] = bench_shm_plane(
                uds_path, workdir, probes, refs, secs)
        finally:
            for s in (jsrv, tsrv, usrv):
                s.shutdown()
                s.server_close()
    fc = rec["paths"]["c_client_uds"].pop("fastconfig", None)
    if isinstance(fc, dict) and "req_per_sec" in fc:
        rec["paths"]["c_fastconfig"] = fc

    # ---- offered overload phase: small queue, open throttle
    print("bench_wire: offered...", file=sys.stderr, flush=True)
    uds2 = os.path.join(workdir, "wire_offered.sock")
    with ServingRuntime(model_str=model_str, batch_window_s=0.0,
                        max_queue=8, max_batch_rows=4096,
                        response_dtype="float32") as rt2:
        _wait_ready(rt2)
        osrv = wire.WireUnixServer(rt2, path=uds2)
        threading.Thread(target=osrv.serve_forever, daemon=True).start()
        try:
            rec["offered"] = bench_offered(
                uds2, workdir, probes, refs, conns=96, secs=secs)
        finally:
            osrv.shutdown()
            osrv.server_close()

    # ---- engine-level predictor phase
    print("bench_wire: predictor...", file=sys.stderr, flush=True)
    rec["predictor"] = bench_predictor(booster, probes,
                                       secs=max(1.0, secs / 2))

    # ---- gates
    jrps = rec["paths"]["json_tcp"]["req_per_sec"]
    uds_rps = rec["paths"]["binary_uds"]["req_per_sec"]
    c_rps = rec["paths"]["c_client_uds"].get("req_per_sec", 0.0)
    best_uds = max(uds_rps, c_rps)
    plane = rec["shm_plane"]
    rec["speedup"] = {
        "binary_uds_over_json": round(best_uds / jrps, 2) if jrps else 0.0,
        "binary_tcp_over_json": round(
            rec["paths"]["binary_tcp"]["req_per_sec"] / jrps, 2)
        if jrps else 0.0,
        "shm_over_uds": plane["speedup_shm_over_uds"],
    }
    all_mis = sum(int(p.get("prediction_mismatches") or 0)
                  for p in rec["paths"].values())
    all_mis += int(rec["offered"].get("prediction_mismatches") or 0)
    all_mis += int(plane.get("prediction_mismatches") or 0)
    c = rec["paths"]["c_client_uds"]
    ring_delta = plane.get("ring_stats_delta") or {}
    rec["gates"] = {
        "binary_uds_ge_5x_json": bool(best_uds >= 5.0 * jrps),
        "offered_ge_10k": bool(
            rec["offered"]["offered_per_sec"] >= 10_000.0),
        "c_client_green": bool(
            c.get("build_rc") == 0 and c.get("socket_rc") == 0
            and c.get("errors") == 0
            and c.get("verify_checked", 0) > 0
            and c.get("prediction_mismatches") == 0),
        "zero_mismatches": bool(all_mis == 0),
        "shm_ge_2x_uds": bool(
            plane["verified"]
            and plane["speedup_shm_over_uds"] >= 2.0),
        "shm_zero_syscalls": bool(
            plane["win_completed"] > 0 and plane["win_syscalls"] == 0),
        "shm_zero_allocs": bool(
            ring_delta.get("sessions", 0) >= 1
            and ring_delta.get("rx_buffer_allocs", 1) == 0
            and ring_delta.get("tx_buffer_allocs", 1)
            <= ring_delta.get("sessions", 0)),
    }
    rec["ok"] = all(rec["gates"].values())
    return rec


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--artifact", default=None,
                    help="write BENCH_WIRE_rNN.json at the repo root "
                         "(schema-validated first)")
    args = ap.parse_args(argv)
    rec = run(quick=args.quick)
    if args.artifact:
        name = os.path.basename(args.artifact)
        rec["artifact"] = name[:-len(".json")] if name.endswith(".json") \
            else name
    else:
        rec["artifact"] = "BENCH_WIRE_adhoc"
    sys.path.insert(0, os.path.join(REPO, "helper"))
    from bench_history import validate_wire_artifact
    problems = validate_wire_artifact(rec)
    out_path = args.artifact or args.out
    if out_path:
        from lightgbm_tpu.runtime import resilience
        resilience.atomic_write(out_path, json.dumps(rec, indent=1) + "\n")
    print(json.dumps(rec))
    if problems:
        for p in problems:
            print("INVALID ARTIFACT: %s" % p, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
