#!/usr/bin/env python
"""Quality-firewall chaos soak (ISSUE 12 acceptance) — CHAOS_QUALITY_r12.

Drives the three-stage model-quality firewall end to end, with real
subprocesses on both sides of the publish seam, under the three new
data/model fault modes:

* **Phase 1 — ingest quarantine + eval gate** (`poison_rows`,
  `label_flip`): a `task=train_online` subprocess is relaunched across
  fault windows while the stream file grows.  Poisoned rows must land
  in the quarantine (never a window), the label-flipped cycle's
  candidate must be REJECTED by the pre-publish gate (persisted as
  ``rejected_<cycle>.txt``, a generation-number hole in the publish
  dir), and — the headline pin — **every published generation, when
  evaluated offline on a clean holdout, never regresses beyond the gate
  tolerance vs its predecessor and never emits a non-finite
  prediction**: injected poison never reaches a published model.
* **Phase 2 — canary + automatic rollback** (`regress_model`): the
  trainer subprocess publishes on a clock with the K-th publish
  sabotaged AFTER its own gate (the regression the offline gate cannot
  see); a serving-replica subprocess consumes the lineage with
  ``canary_fraction`` routing and labeled traffic.  Pins: the bad
  generation is **never served as the incumbent** (zero responses name
  it outside its canary window), the `CanaryPolicy` rolls the fleet
  back (durable ROLLBACK marker in the publish dir), and the rollback
  is **byte-verified** — post-rollback responses equal the restored
  generation's offline predictions for the served path.

Every count in the committed artifact is scraped from the METRICS
REGISTRY (the trainer's ``$LGBM_TPU_METRICS_FILE`` snapshots, the
replica's in-process snapshot), not from driver-side bookkeeping.

Usage:  python exp/chaos_quality.py [artifact.json] [--quick]
        python exp/chaos_quality.py --serve-replica <cfg.json> <out.json>
Env:    CHAOS_QUALITY_SEED, CHAOS_QUALITY_TIMEOUT
"""
from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.runtime import publish, resilience, telemetry  # noqa: E402

SCHEMA_VERSION = 1
ARTIFACT_NAME = "CHAOS_QUALITY_r12"

#: shared training surface: deterministic so relaunches replay cleanly
TRAIN_PARAMS = ["objective=binary", "num_leaves=7", "min_data_in_leaf=5",
                "metric=binary_logloss", "seed=7", "verbose=-1"]
GATE_ARGS = ["publish_gate_tolerance=0.1", "publish_gate_holdout=0.25",
             "online_quarantine_limit=0.6"]
N_FEATURES = 6


def gen_rows(n: int, rng: np.random.Generator) -> np.ndarray:
    X = rng.standard_normal((n, N_FEATURES))
    y = (X[:, 0] + 0.4 * X[:, 1]
         + 0.3 * rng.standard_normal(n) > 0).astype(np.float64)
    return np.column_stack([y, X])


def _append(path: str, rows: np.ndarray) -> None:
    with open(path, "a") as fh:
        np.savetxt(fh, rows, delimiter="\t", fmt="%.8g")


def _run_trainer(workdir: str, cycles: int, fault: Optional[str],
                 metrics_file: str, interval: float = 0.0,
                 timeout: float = 240.0) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("LGBM_TPU_FAULT", None)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
                "LGBM_TPU_METRICS_FILE": metrics_file,
                "JAX_COMPILATION_CACHE_DIR": "/tmp/lgbtpu_jax_cache",
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1"})
    if fault:
        env["LGBM_TPU_FAULT"] = fault
    args = ([sys.executable, "-m", "lightgbm_tpu", "task=train_online",
             "data=train.tsv", "output_model=m.txt",
             "online_cycles=%d" % cycles, "online_rounds=2",
             "online_interval=%g" % interval, "publish_retention=1000",
             "publish_grace=600"] + TRAIN_PARAMS + GATE_ARGS)
    return subprocess.run(args, cwd=workdir, env=env, timeout=timeout,
                          capture_output=True, text=True)


def _scrape_counter(metrics_file: str, name: str,
                    by: Optional[str] = None) -> Dict[str, float]:
    """Per-label sums of one counter family from the LAST registry
    snapshot in a $LGBM_TPU_METRICS_FILE export."""
    out: Dict[str, float] = {}
    try:
        with open(metrics_file) as fh:
            lines = [ln for ln in fh.read().splitlines() if ln.strip()]
        snap = json.loads(lines[-1])
    except (OSError, ValueError, IndexError):
        return out
    fam = snap.get("metrics", {}).get(name, {})
    for entry in fam.get("series", []):
        key = entry.get("labels", {}).get(by, "_total") if by else "_total"
        out[key] = out.get(key, 0.0) + float(entry.get("value", 0.0))
    return out


def _logloss(model_text: str, X: np.ndarray, y: np.ndarray) -> float:
    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_str=model_text, params={"verbose": -1})
    p = np.clip(np.asarray(bst.predict(X)), 1e-12, 1 - 1e-12)
    if not np.isfinite(p).all():
        return float("inf")
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


# ---------------------------------------------------------------------------
# phase 1: quarantine + gate
# ---------------------------------------------------------------------------

def run_phase1(workdir: str, seed: int = 11,
               launch_timeout: float = 240.0) -> Dict[str, Any]:
    os.makedirs(workdir, exist_ok=True)
    rng = np.random.default_rng(seed)
    data = os.path.join(workdir, "train.tsv")
    np.savetxt(data, gen_rows(700, rng), delimiter="\t", fmt="%.8g")
    eval_rows = gen_rows(1500, np.random.default_rng(seed + 999))
    X_eval, y_eval = eval_rows[:, 1:], eval_rows[:, 0]

    launches: List[Dict[str, Any]] = []
    flip_cycle = 3
    plan = [
        # (target cycle count, fault, tag)
        (2, "poison_rows:0.25", "poison"),
        (4, "label_flip:%d" % flip_cycle, "flip"),
        (6, None, "clean"),
    ]
    for i, (cycles, fault, tag) in enumerate(plan, 1):
        mfile = os.path.join(workdir, "metrics_l%d.json" % i)
        r = _run_trainer(workdir, cycles, fault, mfile,
                         timeout=launch_timeout)
        launches.append({
            "tag": tag, "fault": fault, "cycles_target": cycles,
            "rc": r.returncode,
            "quarantined": _scrape_counter(
                mfile, "lgbm_ingest_quarantined_total", by="reason"),
            "gate": _scrape_counter(mfile, "lgbm_publish_gate_total",
                                    by="verdict"),
            "cycles": _scrape_counter(mfile, "lgbm_online_cycles_total",
                                      by="status"),
        })
        if r.returncode != 0:
            launches[-1]["stderr_tail"] = (r.stderr or "")[-1500:]
            break
        _append(data, gen_rows(250, rng))

    pub_dir = os.path.join(workdir, "m.txt.pub")
    published: Dict[int, str] = {}
    for gen, path in publish.generation_paths(pub_dir):
        ok, _ = publish.validate_generation(path)
        if ok:
            with open(path) as fh:
                published[gen] = publish._split_validate(  # noqa: SLF001
                    fh.read())[0]
    rejections = publish.rejection_paths(pub_dir)

    # offline quality ledger: every published generation scored on a
    # CLEAN eval set — the "no poison was ever published" proof
    quality_by_gen = {g: _logloss(t, X_eval, y_eval)
                      for g, t in sorted(published.items())}
    regressions = []
    gens = sorted(quality_by_gen)
    for a, b in zip(gens, gens[1:]):
        la, lb = quality_by_gen[a], quality_by_gen[b]
        if not math.isfinite(lb) or (lb - la) / max(abs(la), 1e-12) > 0.15:
            regressions.append({"from_gen": a, "to_gen": b,
                                "logloss": [la, lb]})

    quarantined_total = sum(
        sum(lnch["quarantined"].values()) for lnch in launches)
    gate_rejects = sum(lnch["gate"].get("reject", 0) for lnch in launches)
    gate_passes = sum(lnch["gate"].get("pass", 0)
                      + lnch["gate"].get("no_incumbent", 0)
                      for lnch in launches)
    rec = {
        "launches": launches,
        "published_generations": gens,
        "rejected_cycles": [c for c, _ in rejections],
        "quarantined_total": int(quarantined_total),
        "gate_rejections": int(gate_rejects),
        "gate_passes": int(gate_passes),
        "offline_logloss_by_generation": {str(g): round(v, 6)
                                          for g, v in
                                          quality_by_gen.items()},
        "published_regressions": regressions,
        "nonfinite_predictions": sum(
            1 for v in quality_by_gen.values() if not math.isfinite(v)),
    }
    rec["ok"] = bool(
        all(lnch["rc"] == 0 for lnch in launches)
        and len(launches) == len(plan)
        and quarantined_total > 0                      # poison was caught
        and gate_rejects >= 1                          # the flip was caught
        and flip_cycle in rec["rejected_cycles"]       # ...and persisted
        and flip_cycle not in gens                     # ...and never shipped
        and rec["nonfinite_predictions"] == 0
        and not regressions                            # published lineage
        and len(gens) >= 4)                            # only ever improves
    return rec


# ---------------------------------------------------------------------------
# phase 2: canary + rollback (the serving replica subprocess)
# ---------------------------------------------------------------------------

def run_serve_replica(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """One serving replica under canary routing + labeled traffic.
    Every response is verified against the offline predictor for the
    generation+path it reports; the record carries the full response
    ledger, the rollback byte-verification, and the registry snapshot."""
    from lightgbm_tpu.runtime.loadgen import ResponseVerifier
    from lightgbm_tpu.runtime.policy import CanaryPolicy
    from lightgbm_tpu.runtime.serving import ServingRuntime

    rng = np.random.default_rng(cfg["seed"])
    probe = rng.standard_normal((8, N_FEATURES))
    labels = (probe[:, 0] + 0.4 * probe[:, 1] > 0).astype(np.float64)
    pol = CanaryPolicy(min_samples=4, patience=2, error_ratio=1.4,
                       error_margin=0.02, promote_after=40)
    rt = ServingRuntime(publish_dir=cfg["pub_dir"], params={"verbose": -1},
                        poll_interval_s=0.05,
                        canary_fraction=float(cfg["canary_fraction"]),
                        canary_policy=pol)
    verifier = ResponseVerifier(probe, pub_dir=cfg["pub_dir"],
                                params={"verbose": -1})
    rt.start()
    deadline = time.monotonic() + 60
    while rt.generation() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    if rt.generation() is None:
        rt.stop()
        raise RuntimeError("replica: no generation in %r" % cfg["pub_dir"])

    responses: List[Dict[str, Any]] = []
    verify_counts: Dict[str, int] = {}
    idx = np.arange(len(probe))
    rollback_verified = None
    rollbacks_seen = 0
    t_end = time.monotonic() + float(cfg["duration_s"])
    while time.monotonic() < t_end:
        incumbent_before = rt.generation()
        canary_before = rt.canary_generation()
        try:
            res = rt.predict(probe, label=labels, deadline_s=5.0)
        except BaseException as e:       # noqa: BLE001 — ledger
            responses.append({"error": "%s: %s" % (type(e).__name__, e)})
            time.sleep(0.05)
            continue
        verdict = verifier.verify(res, idx)
        verify_counts[verdict] = verify_counts.get(verdict, 0) + 1
        responses.append({
            "generation": res.generation, "served_by": res.served_by,
            "incumbent_at_submit": incumbent_before,
            "canary_at_submit": canary_before,
            "verdict": verdict,
        })
        if len(rt.rollback_events) > rollbacks_seen:
            # rollback byte-verification, AT the rollback moment (before
            # a later publish can open a fresh canary or promote): the
            # fleet must now serve the restored generation and its
            # responses must equal that generation's offline predictions
            rollbacks_seen = len(rt.rollback_events)
            restored = rt.rollback_events[-1]["pinned_generation"]
            ok = False
            for _ in range(30):
                r2 = rt.predict(probe, deadline_s=5.0)
                if r2.generation != restored:
                    continue             # a canary-window batch; retry
                refs = verifier.refs(restored)
                ok = bool(refs is not None and np.array_equal(
                    np.asarray(r2.values), refs[r2.served_by][idx]))
                break
            rollback_verified = ok if rollback_verified is None \
                else (rollback_verified and ok)
        time.sleep(float(cfg.get("request_interval_s", 0.04)))

    stats = rt.stats()
    snap = telemetry.snapshot("chaos_quality_replica")
    rt.stop()
    return {
        "responses": responses,
        "verify_counts": verify_counts,
        "stats": {k: stats[k] for k in
                  ("completed", "swaps", "rollbacks", "promotes",
                   "canary_batches", "batches_device", "batches_host")},
        "rollback_events": stats.get("rollback_events", []),
        "rollback_byte_verified": rollback_verified,
        "final_generation": rt.generation(),
        "rollback_marker": publish.read_rollback_marker(cfg["pub_dir"]),
        "snapshot": snap,
    }


def run_phase2(workdir: str, seed: int = 11, canary_fraction: float = 0.25,
               launch_timeout: float = 300.0) -> Dict[str, Any]:
    os.makedirs(workdir, exist_ok=True)
    rng = np.random.default_rng(seed)
    data = os.path.join(workdir, "train.tsv")
    np.savetxt(data, gen_rows(700, rng), delimiter="\t", fmt="%.8g")
    pub_dir = os.path.join(workdir, "m.txt.pub")
    mfile = os.path.join(workdir, "metrics_trainer.json")
    bad_cycle = 3

    env = dict(os.environ)
    env.pop("LGBM_TPU_FAULT", None)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
                "LGBM_TPU_METRICS_FILE": mfile,
                "LGBM_TPU_FAULT": "regress_model:%d" % bad_cycle,
                "JAX_COMPILATION_CACHE_DIR": "/tmp/lgbtpu_jax_cache",
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1"})
    interval = 1.5
    cycles = 5
    trainer_args = ([sys.executable, "-m", "lightgbm_tpu",
                     "task=train_online", "data=train.tsv",
                     "output_model=m.txt", "online_cycles=%d" % cycles,
                     "online_rounds=2", "online_interval=%g" % interval,
                     "publish_retention=1000", "publish_grace=600"]
                    + TRAIN_PARAMS + GATE_ARGS)
    t_log = open(os.path.join(workdir, "trainer.log"), "w")
    trainer = subprocess.Popen(trainer_args, cwd=workdir, env=env,
                               stdout=t_log, stderr=subprocess.STDOUT)
    try:
        # wait for generation 1, then launch the replica SUBPROCESS
        sub = publish.ModelSubscriber(pub_dir, attempts=1)
        deadline = time.monotonic() + 120
        while sub.resolve_once() is None:
            if trainer.poll() is not None:
                raise RuntimeError("trainer died before first publish")
            if time.monotonic() > deadline:
                raise RuntimeError("no generation published in time")
            time.sleep(0.1)
        cfg = {"pub_dir": pub_dir, "seed": seed + 1,
               "canary_fraction": canary_fraction,
               "duration_s": interval * (cycles + 3)}
        cfg_path = os.path.join(workdir, "replica.json")
        out_path = os.path.join(workdir, "replica.out.json")
        with open(cfg_path, "w") as fh:
            json.dump(cfg, fh)
        renv = dict(env)
        renv.pop("LGBM_TPU_FAULT", None)
        renv.pop("LGBM_TPU_METRICS_FILE", None)
        rlog = open(os.path.join(workdir, "replica.log"), "w")
        replica = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--serve-replica",
             cfg_path, out_path],
            cwd=workdir, env=renv, stdout=rlog, stderr=subprocess.STDOUT)
        rrc = replica.wait(timeout=launch_timeout)
        rlog.close()
        if rrc != 0:
            with open(rlog.name) as fh:
                raise RuntimeError("replica failed rc=%d: %s"
                                   % (rrc, fh.read()[-2000:]))
        trc = trainer.wait(timeout=launch_timeout)
    finally:
        if trainer.poll() is None:
            trainer.kill()
            trainer.wait(timeout=30)
        t_log.close()
    with open(out_path) as fh:
        rep = json.load(fh)

    canary_events = _sum_snapshot_counter(rep["snapshot"],
                                          "lgbm_canary_events_total",
                                          by="event")
    canary_batches = _sum_snapshot_counter(rep["snapshot"],
                                           "lgbm_canary_batches_total",
                                           by="kind")
    # the regressed generation must NEVER have been the incumbent: every
    # response naming it must have been a canary-window batch
    bad_outside_canary = [
        r for r in rep["responses"]
        if r.get("generation") == bad_cycle
        and r.get("incumbent_at_submit") == bad_cycle]
    bad_responses = sum(1 for r in rep["responses"]
                        if r.get("generation") == bad_cycle)
    verify = rep["verify_counts"]
    rec = {
        "trainer_rc": trc,
        "bad_generation": bad_cycle,
        "canary_fraction": canary_fraction,
        "responses_total": len(rep["responses"]),
        "responses_bad_generation": int(bad_responses),
        "responses_bad_outside_canary": len(bad_outside_canary),
        "verify_counts": verify,
        "canary_events": {k: int(v) for k, v in canary_events.items()},
        "canary_batches": {k: int(v) for k, v in canary_batches.items()},
        "rollback_count": int(rep["stats"]["rollbacks"]),
        "canary_batches_to_rollback": (
            rep["rollback_events"][-1].get("canary_batches")
            if rep["rollback_events"] else None),
        "rollback_byte_verified": rep["rollback_byte_verified"],
        "rollback_marker": rep["rollback_marker"],
        "final_generation": rep["final_generation"],
        "trainer_generations": _scrape_counter(
            mfile, "lgbm_online_cycles_total", by="status"),
    }
    total_batches = sum(canary_batches.values())
    canary_share = (canary_batches.get("canary", 0) / total_batches
                    if total_batches else 0.0)
    rec["canary_batch_share"] = round(canary_share, 4)
    rec["ok"] = bool(
        trc == 0
        and rec["rollback_count"] >= 1
        and canary_events.get("rollback", 0) >= 1
        and rec["responses_bad_outside_canary"] == 0
        and bad_cycle in rep["rollback_marker"].get("bad_generations", [])
        and rec["rollback_byte_verified"] is True
        and verify.get("ok", 0) > 0
        and verify.get("mismatch", 0) == 0
        and verify.get("wrong_generation", 0) == 0
        # routing held the canary near its configured share
        and canary_share <= canary_fraction + 0.15)
    return rec


def _sum_snapshot_counter(snap: Dict[str, Any], name: str,
                          by: Optional[str] = None) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for entry in snap.get("metrics", {}).get(name, {}).get("series", []):
        key = entry.get("labels", {}).get(by, "_total") if by else "_total"
        out[key] = out.get(key, 0.0) + float(entry.get("value", 0.0))
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_soak(workdir: str, seed: int = 11, quick: bool = False,
             launch_timeout: float = 300.0) -> Dict[str, Any]:
    t0 = time.monotonic()
    rec: Dict[str, Any] = {
        "artifact": ARTIFACT_NAME,
        "schema_version": SCHEMA_VERSION,
        "t_start": resilience.wallclock(),
        "seed": seed,
        "phases": {},
    }
    rec["phases"]["ingest_gate"] = run_phase1(
        os.path.join(workdir, "phase1"), seed=seed,
        launch_timeout=launch_timeout)
    if not quick:
        rec["phases"]["canary"] = run_phase2(
            os.path.join(workdir, "phase2"), seed=seed,
            launch_timeout=launch_timeout)
    rec["elapsed_s"] = round(time.monotonic() - t0, 1)
    rec["ok"] = all(p["ok"] for p in rec["phases"].values())
    return rec


def main(argv: List[str]) -> int:
    if len(argv) > 1 and argv[1] == "--serve-replica":
        with open(argv[2]) as fh:
            cfg = json.load(fh)
        rec = run_serve_replica(cfg)
        resilience.atomic_write(argv[3], json.dumps(rec))
        return 0
    import tempfile
    quick = "--quick" in argv
    args = [a for a in argv[1:] if not a.startswith("--")]
    artifact = args[0] if args else os.path.join(REPO,
                                                 ARTIFACT_NAME + ".json")
    seed = int(os.environ.get("CHAOS_QUALITY_SEED", "11"))
    timeout = float(os.environ.get("CHAOS_QUALITY_TIMEOUT", "300"))
    with tempfile.TemporaryDirectory(prefix="lgbm_chaos_q_") as wd:
        rec = run_soak(wd, seed=seed, quick=quick, launch_timeout=timeout)
    from helper.bench_history import validate_quality_artifact
    problems = validate_quality_artifact(rec)
    if problems:
        debug = artifact + ".invalid"
        resilience.atomic_write(debug, json.dumps(rec, indent=1) + "\n")
        print("chaos_quality: INVALID artifact (debug copy at %s): %s"
              % (debug, "; ".join(problems)))
        return 2
    resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
    p1 = rec["phases"]["ingest_gate"]
    p2 = rec["phases"].get("canary", {})
    print("chaos_quality: ok=%s quarantined=%d gate_rejections=%d "
          "published=%s rollbacks=%s rollback_byte_verified=%s "
          "elapsed=%.0fs artifact=%s"
          % (rec["ok"], p1["quarantined_total"], p1["gate_rejections"],
             p1["published_generations"], p2.get("rollback_count", "-"),
             p2.get("rollback_byte_verified", "-"), rec["elapsed_s"],
             artifact), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
