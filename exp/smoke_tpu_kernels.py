"""Smoke: run the Pallas segment kernels on the REAL TPU vs the portable path."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops import segment as seg
from lightgbm_tpu.ops import pallas_segment as pseg

print("backend:", jax.default_backend(), flush=True)
rng = np.random.default_rng(0)
N, F = 4096, 6
B = 64
P = 128  # lane-aligned payload width, as the fast path provides on TPU
GRAD, HESS, CNT, VAL = F, F + 1, F + 2, F + 3

payload = np.zeros((N + seg.GUARD, P), np.float32)
payload[:N, :F] = rng.integers(0, B, (N, F))
payload[:N, GRAD] = rng.standard_normal(N)
payload[:N, HESS] = rng.random(N) + 0.1
payload[:N, CNT] = 1.0
payload = jnp.asarray(payload)
aux = jnp.zeros_like(payload)

start, count = jnp.int32(128), jnp.int32(3000)

t0 = time.time()
h_pl = pseg.segment_histogram(payload, start, count, num_features=F,
                              num_bins=B, grad_col=GRAD, hess_col=HESS,
                              cnt_col=CNT)
jax.block_until_ready(h_pl)
print("pallas hist compile+run %.1fs" % (time.time() - t0), flush=True)
h_ref = seg.segment_histogram(payload, start, count, num_features=F,
                              num_bins=B, grad_col=GRAD, hess_col=HESS,
                              cnt_col=CNT)
err = float(jnp.abs(h_pl - h_ref).max())
print("hist max abs err:", err, flush=True)
assert err < 1e-3, err

pred = seg.SplitPredicate(
    col=jnp.int32(2), threshold=jnp.int32(30),
    default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
    missing_type=jnp.int32(0), num_bin=jnp.int32(B),
    default_bin=jnp.int32(0), offset=jnp.int32(0),
    identity=jnp.bool_(True), bitset=jnp.zeros(B, jnp.int32))

t0 = time.time()
p_pl, a_pl, nl_pl = pseg.partition_segment(
    payload, aux, start, count, pred, jnp.float32(1.5), jnp.float32(-2.5),
    VAL, B)
jax.block_until_ready(p_pl)
print("pallas partition compile+run %.1fs" % (time.time() - t0), flush=True)
p_ref, a_ref, nl_ref = seg.partition_segment(
    payload, aux, start, count, pred, jnp.float32(1.5), jnp.float32(-2.5),
    VAL)
print("num_left pallas=%d ref=%d" % (int(nl_pl), int(nl_ref)), flush=True)
assert int(nl_pl) == int(nl_ref)
perr = float(jnp.abs(p_pl - p_ref).max())
print("partition payload max abs err:", perr, flush=True)
assert perr < 1e-5, perr
print("SMOKE OK", flush=True)


# --- round-4 additions: feature-TILED histogram at wide-benchmark shapes
# (MS-LTR 137x256, Expo 700x256) with the double-buffered chunk DMA ---
for (Fw, Bw) in ((137, 256), (700, 256), (968, 64), (2000, 64)):
    assert pseg.fits_vmem(Fw, Bw), (Fw, Bw)
    Pw = -(-(Fw + 12) // 128) * 128
    gcol, hcol, ccol = Fw, Fw + 1, Fw + 2
    pay_w = np.zeros((2048 + seg.GUARD, Pw), np.float32)
    pay_w[:2048, :Fw] = rng.integers(0, Bw, (2048, Fw))
    pay_w[:2048, gcol] = rng.standard_normal(2048)
    pay_w[:2048, hcol] = rng.random(2048) + 0.1
    pay_w[:2048, ccol] = 1.0
    pay_w = jnp.asarray(pay_w)
    s_w, c_w = jnp.int32(256), jnp.int32(1500)
    t0 = time.time()
    h_w = pseg.segment_histogram(pay_w, s_w, c_w, num_features=Fw,
                                 num_bins=Bw, grad_col=gcol, hess_col=hcol,
                                 cnt_col=ccol)
    jax.block_until_ready(h_w)
    print("tiled hist %dx%d compile+run %.1fs" % (Fw, Bw, time.time() - t0),
          flush=True)
    h_wref = seg.segment_histogram(pay_w, s_w, c_w, num_features=Fw,
                                   num_bins=Bw, grad_col=gcol, hess_col=hcol,
                                   cnt_col=ccol)
    err = float(jnp.abs(h_w - h_wref).max())
    print("tiled hist %dx%d max abs err: %s" % (Fw, Bw, err), flush=True)
    assert err < 1e-2, err
print("tiled + double-buffered histogram kernels OK on", jax.default_backend(),
      flush=True)


# --- precision: the MXU's default f32 matmul is ONE bf16 pass, which (before
# the HIGHEST/part-decomposition fixes) rounded every permuted payload value
# to 8 mantissa bits and collapsed the radix-4096 idx columns.  These checks
# only bite on real hardware — interpret mode is plain f32.  ---
IDX = F + 4
payx = np.zeros((8192 + seg.GUARD, P), np.float32)
payx[:8192, :F] = rng.integers(0, B, (8192, F))
gvals = (1.0 + rng.random(8192) * 2.0**-18).astype(np.float32)  # >8 mantissa bits
payx[:8192, GRAD] = gvals
payx[:8192, HESS] = 1.0
payx[:8192, CNT] = 1.0
payx[:8192, IDX] = np.arange(8192, dtype=np.float32) % 4096
p_x, _, _ = pseg.partition_segment(
    jnp.asarray(payx), jnp.zeros_like(jnp.asarray(payx)), jnp.int32(0),
    jnp.int32(8192), pred, jnp.float32(1.0), jnp.float32(-1.0), VAL, B)
p_x = np.asarray(p_x)
assert np.array_equal(np.sort(p_x[:8192, IDX]), np.sort(payx[:8192, IDX])), \
    "idx columns corrupted by the partition matmul"
assert np.array_equal(np.sort(p_x[:8192, GRAD]), np.sort(gvals)), \
    "payload values bf16-rounded by the partition matmul"
h_x = pseg.segment_histogram(jnp.asarray(payx), jnp.int32(0), jnp.int32(8192),
                             num_features=F, num_bins=B, grad_col=GRAD,
                             hess_col=HESS, cnt_col=CNT)
h64 = np.zeros((F, B), np.float64)
for f in range(F):
    np.add.at(h64[f], payx[:8192, f].astype(np.int64), gvals.astype(np.float64))
gerr = float(np.abs(np.asarray(h_x)[:, :, 0] - h64).max())
print("hist grad-sum err vs float64: %.3g" % gerr, flush=True)
assert gerr < 1e-3, gerr   # f32-accumulation class, NOT bf16-input class (~0.5)
print("PRECISION OK: exact permutation + f32-class histograms on",
      jax.default_backend(), flush=True)


# --- accumulator-window partition kernel: Mosaic-compile + exactness +
# speed vs the RMW kernel.  Flip pseg.PARTITION_ACC_VALIDATED once this
# section is green on real hardware. ---
import time as _t
for (s_a, c_a) in ((128, 3000), (7, 8000), (513, 256), (0, 8192)):
    p_a, a_a, nl_a = pseg.partition_segment_acc(
        jnp.asarray(payx), jnp.zeros_like(jnp.asarray(payx)),
        jnp.int32(s_a), jnp.int32(c_a), pred, jnp.float32(1.5),
        jnp.float32(-2.5), VAL, B)
    p_r, a_r, nl_r = seg.partition_segment(
        jnp.asarray(payx), jnp.zeros_like(jnp.asarray(payx)),
        jnp.int32(s_a), jnp.int32(c_a), pred, jnp.float32(1.5),
        jnp.float32(-2.5), VAL)
    assert int(nl_a) == int(nl_r), (s_a, c_a, int(nl_a), int(nl_r))
    err_a = float(jnp.abs(p_a - p_r).max())
    print("acc partition (%d,%d): nl=%d err=%s" % (s_a, c_a, int(nl_a), err_a),
          flush=True)
    assert err_a == 0.0, err_a
p_roll, _, nl_roll = pseg.partition_segment_acc(
    jnp.asarray(payx), jnp.zeros_like(jnp.asarray(payx)), jnp.int32(7),
    jnp.int32(8000), pred, jnp.float32(1.5), jnp.float32(-2.5), VAL, B,
    roll_place=True)
p_rollref, _, nl_rollref = seg.partition_segment(
    jnp.asarray(payx), jnp.zeros_like(jnp.asarray(payx)), jnp.int32(7),
    jnp.int32(8000), pred, jnp.float32(1.5), jnp.float32(-2.5), VAL)
assert int(nl_roll) == int(nl_rollref)
err_roll = float(jnp.abs(p_roll - p_rollref).max())
print("acc+roll partition err:", err_roll, flush=True)
assert err_roll == 0.0, err_roll
for name, fn in (("rmw", lambda p_, a_: pseg.partition_segment(
                     p_, a_, jnp.int32(0), jnp.int32(8192), pred,
                     jnp.float32(1.), jnp.float32(-1.), VAL, B)),
                 ("acc", lambda p_, a_: pseg.partition_segment_acc(
                     p_, a_, jnp.int32(0), jnp.int32(8192), pred,
                     jnp.float32(1.), jnp.float32(-1.), VAL, B,
                     roll_place=False)),
                 ("acc+roll", lambda p_, a_: pseg.partition_segment_acc(
                     p_, a_, jnp.int32(0), jnp.int32(8192), pred,
                     jnp.float32(1.), jnp.float32(-1.), VAL, B,
                     roll_place=True))):
    ts = []
    for _ in range(5):
        p_, a_ = jnp.asarray(payx), jnp.zeros_like(jnp.asarray(payx))
        _ = np.asarray(p_)[0, 0]
        t0 = _t.perf_counter()
        nl_ = int(fn(p_, a_)[2])
        ts.append(_t.perf_counter() - t0)
    print("partition[%s] 8192 rows: median %.2f ms (fetch-forced)"
          % (name, sorted(ts)[2] * 1e3), flush=True)
print("ACC PARTITION OK on", jax.default_backend(), flush=True)


# --- repeat-based one-hot expansion: Mosaic-compile + exactness + speed
# vs the expand-matmul histogram.  Flip pseg.HIST_REPEAT_VALIDATED once
# green here. ---
for (Fr, Br) in ((28, 256), (137, 256), (700, 256)):
    Pr = -(-(Fr + 12) // 128) * 128
    gc, hc, cc = Fr, Fr + 1, Fr + 2
    pay_r = np.zeros((8192 + seg.GUARD, Pr), np.float32)
    pay_r[:8192, :Fr] = rng.integers(0, Br, (8192, Fr))
    pay_r[:8192, gc] = rng.standard_normal(8192)
    pay_r[:8192, hc] = rng.random(8192) + 0.1
    pay_r[:8192, cc] = 1.0
    pay_r = jnp.asarray(pay_r)
    kw = dict(num_features=Fr, num_bins=Br, grad_col=gc, hess_col=hc,
              cnt_col=cc)
    h_m = pseg.segment_histogram(pay_r, jnp.int32(128), jnp.int32(7000),
                                 expand_impl="matmul", **kw)
    h_r = pseg.segment_histogram(pay_r, jnp.int32(128), jnp.int32(7000),
                                 expand_impl="repeat", **kw)
    err_r = float(jnp.abs(np.asarray(h_m) - np.asarray(h_r)).max())
    print("repeat hist %dx%d max abs err vs matmul: %s" % (Fr, Br, err_r),
          flush=True)
    assert err_r < 1e-4, err_r
    for label in ("matmul", "repeat"):
        ts = []
        for i in range(5):
            t0 = _t.perf_counter()
            h_ = np.asarray(pseg.segment_histogram(
                pay_r, jnp.int32(0), jnp.int32(8192 - i),
                expand_impl=label, **kw))[0, 0, 2]
            ts.append(_t.perf_counter() - t0)
        print("hist[%s] %dx%d 8192 rows: median %.2f ms (fetch-forced)"
              % (label, Fr, Br, sorted(ts)[2] * 1e3), flush=True)
print("REPEAT HIST OK on", jax.default_backend(), flush=True)


# --- merged partition+hist kernel: Mosaic-compile + exactness + speed vs
# the split acc-partition + hist pair.  Flip pseg.PARTITION_HIST_VALIDATED
# once this section is green on real hardware. ---
MF, MB = 28, 256
MP = 128
mg, mh, mc, MVAL = MF, MF + 1, MF + 2, MF + 3
pay_m = np.zeros((8192 + seg.GUARD, MP), np.float32)
pay_m[:8192, :MF] = rng.integers(0, MB, (8192, MF))
pay_m[:8192, mg] = rng.standard_normal(8192)
pay_m[:8192, mh] = rng.random(8192) + 0.1
pay_m[:8192, mc] = 1.0
pay_m = jnp.asarray(pay_m)
pred_m = seg.SplitPredicate(
    col=jnp.int32(2), threshold=jnp.int32(100),
    default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
    missing_type=jnp.int32(0), num_bin=jnp.int32(MB),
    default_bin=jnp.int32(0), offset=jnp.int32(0),
    identity=jnp.bool_(True), bitset=jnp.zeros(MB, jnp.int32))
mkw = dict(num_features=MF, grad_col=mg, hess_col=mh, cnt_col=mc)
for (s_m, c_m) in ((128, 3000), (7, 8000), (513, 256)):
    p_m, a_m, nl_m, hl_m, hr_m = pseg.partition_segment_hist(
        pay_m, jnp.zeros_like(pay_m), jnp.int32(s_m), jnp.int32(c_m),
        pred_m, jnp.float32(1.5), jnp.float32(-2.5), MVAL, MB, **mkw)
    p_mr, _, nl_mr = seg.partition_segment(
        pay_m, jnp.zeros_like(pay_m), jnp.int32(s_m), jnp.int32(c_m),
        pred_m, jnp.float32(1.5), jnp.float32(-2.5), MVAL)
    assert int(nl_m) == int(nl_mr), (s_m, c_m, int(nl_m), int(nl_mr))
    perr_m = float(jnp.abs(p_m - p_mr).max())
    hl_ref = seg.segment_histogram(p_mr, jnp.int32(s_m), nl_mr,
                                   num_bins=MB, **mkw)
    hr_ref = seg.segment_histogram(p_mr, jnp.int32(s_m) + nl_mr,
                                   jnp.int32(c_m) - nl_mr,
                                   num_bins=MB, **mkw)
    herr = max(float(jnp.abs(hl_m - hl_ref).max()),
               float(jnp.abs(hr_m - hr_ref).max()))
    print("merged part+hist (%d,%d): nl=%d perr=%s herr=%.3g"
          % (s_m, c_m, int(nl_m), perr_m, herr), flush=True)
    assert perr_m == 0.0, perr_m
    assert herr < 1e-3, herr
# race: merged kernel vs (acc partition + one smaller-child hist) — the
# product's per-split device work in each mode


def _split_mode(p_, a_):
    h_ = pseg.segment_histogram(p_, jnp.int32(0), jnp.int32(4096),
                                num_bins=MB, **mkw)
    out_ = pseg.partition_segment_acc(
        p_, a_, jnp.int32(0), jnp.int32(8192), pred_m,
        jnp.float32(1.), jnp.float32(-1.), MVAL, MB)
    jax.block_until_ready(h_)
    return out_


def _merged_mode(p_, a_):
    return pseg.partition_segment_hist(
        p_, a_, jnp.int32(0), jnp.int32(8192), pred_m,
        jnp.float32(1.), jnp.float32(-1.), MVAL, MB, **mkw)


for name, fn in (("split: acc+hist", _split_mode), ("merged", _merged_mode)):
    ts = []
    for _ in range(5):
        p_, a_ = jnp.asarray(pay_m), jnp.zeros_like(pay_m)
        _ = np.asarray(p_)[0, 0]
        t0 = _t.perf_counter()
        nl_ = int(fn(p_, a_)[2])
        ts.append(_t.perf_counter() - t0)
    print("per-split device work[%s] 8192 rows: median %.2f ms (fetch-forced)"
          % (name, sorted(ts)[2] * 1e3), flush=True)
print("MERGED PART+HIST OK on", jax.default_backend(), flush=True)


# --- column-block histogram engine: Mosaic-compile + exactness at an
# ultra-wide payload (the raw-Allstate / Epsilon class that overflows the
# single-pass plan), including the two-window DMA the single-pass kernel
# never issues.  Flip pseg.HIST_COLBLOCK_VALIDATED once this section is
# green on real hardware. ---
CBF, CBB = 1500, 64            # spans 3 column blocks + ragged tail
CBP = -(-(CBF + 8) // 128) * 128
pay_cb = np.zeros((8192 + seg.GUARD, CBP), np.float32)
pay_cb[:8192, :CBF] = rng.integers(0, CBB, (8192, CBF))
pay_cb[:8192, CBF] = rng.standard_normal(8192)
pay_cb[:8192, CBF + 1] = rng.random(8192) + 0.1
pay_cb[:8192, CBF + 2] = 1.0
pay_cb = jnp.asarray(pay_cb)
cbkw = dict(num_features=CBF, num_bins=CBB, grad_col=CBF,
            hess_col=CBF + 1, cnt_col=CBF + 2)
assert pseg.fits_vmem_colblock(CBF, CBB, CBP, CBF, CBF + 1, CBF + 2)
for (s_cb, c_cb) in ((0, 8000), (7, 4097), (513, 256)):
    h_cb = pseg.segment_histogram_colblock(
        pay_cb, jnp.int32(s_cb), jnp.int32(c_cb), **cbkw)
    h_ref = seg.segment_histogram(pay_cb, jnp.int32(s_cb),
                                  jnp.int32(c_cb), **cbkw)
    err_cb = float(jnp.abs(h_cb - h_ref).max())
    print("colblock hist (%d,%d): err=%.3g" % (s_cb, c_cb, err_cb),
          flush=True)
    assert err_cb < 1e-3, err_cb
ts = []
for i in range(5):
    t0 = _t.perf_counter()
    _ = np.asarray(pseg.segment_histogram_colblock(
        pay_cb, jnp.int32(0), jnp.int32(8192 - i), **cbkw))[0, 0, 2]
    ts.append(_t.perf_counter() - t0)
print("colblock hist %dx%d 8192 rows: median %.2f ms (fetch-forced)"
      % (CBF, CBB, sorted(ts)[2] * 1e3), flush=True)
print("COLBLOCK HIST OK on", jax.default_backend(), flush=True)


# --- 4-deep read ring for the acc partition: Mosaic-compile + exactness
# + race vs the validated 2-deep ring (the per-chunk DMA wait is the
# measured bottleneck; depth 4 issues three chunks ahead).  Flip
# pseg.PARTITION_RING4_VALIDATED once green AND the race favors (or
# ties) depth 4. ---
for rd in (2, 4):
    p_r4, _, nl_r4 = pseg.partition_segment_acc(
        jnp.asarray(pay_m), jnp.zeros_like(pay_m), jnp.int32(128),
        jnp.int32(7000), pred_m, jnp.float32(1.5), jnp.float32(-2.5),
        MVAL, MB, ring_depth=rd)
    if rd == 2:
        p_ref_r, nl_ref_r = np.asarray(p_r4), int(nl_r4)
    else:
        assert int(nl_r4) == nl_ref_r
        err_r4 = float(np.abs(np.asarray(p_r4) - p_ref_r).max())
        print("ring4 vs ring2 exactness: err=%.3g" % err_r4, flush=True)
        assert err_r4 == 0.0, err_r4
for rd in (2, 4):
    ts = []
    for _ in range(5):
        p_, a_ = jnp.asarray(pay_m), jnp.zeros_like(pay_m)
        _ = np.asarray(p_)[0, 0]
        t0 = _t.perf_counter()
        nl_ = int(pseg.partition_segment_acc(
            p_, a_, jnp.int32(0), jnp.int32(8192), pred_m,
            jnp.float32(1.), jnp.float32(-1.), MVAL, MB,
            ring_depth=rd)[2])
        ts.append(_t.perf_counter() - t0)
    print("acc partition ring=%d 8192 rows: median %.2f ms (fetch-forced)"
          % (rd, sorted(ts)[2] * 1e3), flush=True)
print("RING OK on", jax.default_backend(), flush=True)
# the flip also switches the MERGED kernel's ring: validate it at depth 4
p_m4, _, nl_m4, hl_m4, hr_m4 = pseg.partition_segment_hist(
    jnp.asarray(pay_m), jnp.zeros_like(pay_m), jnp.int32(128),
    jnp.int32(7000), pred_m, jnp.float32(1.5), jnp.float32(-2.5),
    MVAL, MB, ring_depth=4, **mkw)
p_m2, _, nl_m2, hl_m2, hr_m2 = pseg.partition_segment_hist(
    jnp.asarray(pay_m), jnp.zeros_like(pay_m), jnp.int32(128),
    jnp.int32(7000), pred_m, jnp.float32(1.5), jnp.float32(-2.5),
    MVAL, MB, ring_depth=2, **mkw)
assert int(nl_m4) == int(nl_m2)
err_m4 = max(float(jnp.abs(p_m4 - p_m2).max()),
             float(jnp.abs(hl_m4 - hl_m2).max()),
             float(jnp.abs(hr_m4 - hr_m2).max()))
print("merged kernel ring4 vs ring2: err=%.3g" % err_m4, flush=True)
assert err_m4 == 0.0, err_m4
print("RING(MERGED) OK on", jax.default_backend(), flush=True)


# --- column-block PARTITION: Mosaic-compile + exactness at an ultra-wide
# payload (Epsilon/raw-Allstate class; the full-width partition kernels
# cannot plan VMEM there).  Includes the one new Mosaic pattern of the
# family: the snapshot kernel's traced-but-128-aligned lane base.  Flip
# pseg.PARTITION_BLOCKS_VALIDATED once green and the race beats the
# portable partition. ---
PBF, PBB = 1200, 64
PBP = -(-(PBF + 8) // 128) * 128
pay_pb = np.zeros((8192 + seg.GUARD, PBP), np.float32)
pay_pb[:8192, :PBF] = rng.integers(0, PBB, (8192, PBF))
pay_pb[:8192, PBF] = rng.standard_normal(8192)
pay_pb[:8192, PBF + 1] = rng.random(8192) + 0.1
pay_pb[:8192, PBF + 2] = 1.0
pay_pb = jnp.asarray(pay_pb)
PBVAL = PBF + 3
pred_pb = seg.SplitPredicate(
    col=jnp.int32(700), threshold=jnp.int32(30),
    default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
    missing_type=jnp.int32(0), num_bin=jnp.int32(PBB),
    default_bin=jnp.int32(0), offset=jnp.int32(0),
    identity=jnp.bool_(True), bitset=jnp.zeros(PBB, jnp.int32))
for (s_pb, c_pb) in ((128, 3000), (7, 8000), (513, 256)):
    p_pb, _, nl_pb = pseg.partition_segment_acc_blocks(
        pay_pb, jnp.zeros_like(pay_pb), jnp.int32(s_pb), jnp.int32(c_pb),
        pred_pb, jnp.float32(1.5), jnp.float32(-2.5), PBVAL, PBB)
    p_pr, _, nl_pr = seg.partition_segment(
        pay_pb, jnp.zeros_like(pay_pb), jnp.int32(s_pb), jnp.int32(c_pb),
        pred_pb, jnp.float32(1.5), jnp.float32(-2.5), PBVAL)
    assert int(nl_pb) == int(nl_pr), (s_pb, c_pb, int(nl_pb), int(nl_pr))
    err_pb = float(jnp.abs(p_pb - p_pr).max())
    print("blocks partition (%d,%d): nl=%d err=%.3g"
          % (s_pb, c_pb, int(nl_pb), err_pb), flush=True)
    assert err_pb == 0.0, err_pb
for name, fn in (
    ("portable", lambda p_, a_: seg.partition_segment(
        p_, a_, jnp.int32(0), jnp.int32(8192), pred_pb,
        jnp.float32(1.), jnp.float32(-1.), PBVAL)),
    ("blocks", lambda p_, a_: pseg.partition_segment_acc_blocks(
        p_, a_, jnp.int32(0), jnp.int32(8192), pred_pb,
        jnp.float32(1.), jnp.float32(-1.), PBVAL, PBB)),
):
    ts = []
    for _ in range(5):
        p_, a_ = jnp.asarray(pay_pb), jnp.zeros_like(pay_pb)
        _ = np.asarray(p_)[0, 0]
        t0 = _t.perf_counter()
        out_ = fn(p_, a_)
        _ = np.asarray(out_[0])[0, 0]
        ts.append(_t.perf_counter() - t0)
    print("ultra-wide partition[%s] 8192x%d rows: median %.2f ms "
          "(fetch-forced)" % (name, PBP, sorted(ts)[2] * 1e3), flush=True)
print("BLOCKS PARTITION OK on", jax.default_backend(), flush=True)
