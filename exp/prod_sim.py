#!/usr/bin/env python
"""Closed-loop production simulation (ISSUE 11 acceptance).

Exercises everything PRs 6-10 built as ONE system under load: a
deterministic open-loop load generator (runtime/loadgen.py) drives a
REPLICATED serving fleet — N `ServingRuntime` subprocesses sharing one
publish directory through the concurrent-reader subscriber seam — while
the continuous trainer (`task=train_online`, its own subprocess) ingests
a GROWING stream and publishes on its absolute-clock schedule, and
`LGBM_TPU_FAULT` device kill/stall churn runs throughout.  The serving
replicas exercise the full ISSUE 11 knob set: priority classes with
per-class queue reservations, per-model quotas, and the queue-depth
hysteresis autoscale/shed policy.

Three scenarios ride the same harness: **binary**, **multiclass**, and
**lambdarank** ranking (the online path's newest workload — the stream
carries a query-id column, the rolling window trims on group
boundaries).

Every number in the committed ``SIM_r11.json`` artifact is scraped from
the METRICS REGISTRY of the replica processes (latency/staleness
histograms, per-class offered/shed counters, verification verdicts,
policy decisions), not from client-side stopwatches.  The correctness
bar is the chaos-soak bar, continuously applied: zero wrong-generation
responses and byte-identity of every completed response against the
offline predictor for the generation it reports.

ISSUE 17 adds ``--fleet``: the ELASTIC variant of the same harness — a
`FleetController` (runtime/fleet.py) autoscales replica subprocesses
against a p99 SLO under >=10x the r11 offered load, across a
120-tenant model zoo with bounded LRU residency, `die_at_spawn` +
mid-run SIGKILL churn, shed strictly as the last resort.  Artifact:
``SIM_r17.json``; runbook: docs/PRODSIM.md "Autoscaler runbook".

Usage:  python exp/prod_sim.py [artifact.json] [--quick]
        (default artifact: SIM_r11.json at the repo root; --quick runs
        the reduced binary-only smoke the tier-1 test uses)
        python exp/prod_sim.py [artifact.json] --fleet [--quick]
        (elastic-fleet scenarios -> SIM_r17.json; --quick runs the
        short diurnal-only smoke, gates not expected to pass at that
        duration)
        python exp/prod_sim.py --replica <cfg.json> <out.json>
        (internal: one serving replica + load generator)
Env:    PROD_SIM_SEED, PROD_SIM_REPLICAS, PROD_SIM_DURATION,
        PROD_SIM_LOAD_SCALE (--fleet: scales every shape's rps)
"""
from __future__ import annotations

import glob
import json
import math
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.runtime import publish, resilience, telemetry, \
    tracing, warmup  # noqa: E402

SCHEMA_VERSION = 1

#: trace-artifact schema (ISSUE 14): the merged Perfetto timeline +
#: machine gates committed as TRACE_r*.json
TRACE_SCHEMA_VERSION = 1

#: merged-trace size bound for the committed artifact (newest slices
#: kept; the cut is recorded, never silent)
TRACE_MAX_EVENTS = 20000

#: serving-side fault windows a replica's churn thread draws from
#: (None = quiet step); the armed fault kills or stalls every device
#: batch, so the replica must degrade to the host path and recover.
FAULT_POOL = [None, None, "die_at_predict:1", "slow_predict:0.6"]

#: the three workloads; `query` marks the ranking stream layout
#: (label, qid, features) consumed via query_column=0.
SCENARIOS: Dict[str, Dict[str, Any]] = {
    "binary": {
        "objective": "binary", "n_features": 8, "num_class": 1,
        "shape": {"kind": "diurnal"},
        "train_params": {"objective": "binary", "num_leaves": 15},
    },
    "multiclass": {
        "objective": "multiclass", "n_features": 8, "num_class": 4,
        "shape": {"kind": "bursty"},
        "train_params": {"objective": "multiclass", "num_class": 4,
                         "num_leaves": 15},
    },
    "lambdarank": {
        "objective": "lambdarank", "n_features": 8, "num_class": 1,
        "query": True, "query_rows": 8,
        "shape": {"kind": "step"},
        "train_params": {"objective": "lambdarank", "num_leaves": 15,
                         "min_data_in_leaf": 5},
    },
}


# ---------------------------------------------------------------------------
# stream data
# ---------------------------------------------------------------------------

def gen_rows(spec: Dict[str, Any], n: int, rng: np.random.Generator,
             next_qid: int = 0):
    """(file_rows, next_qid): one deterministic chunk of the scenario's
    stream file.  Ranking rows carry a globally increasing qid column so
    appended chunks keep query groups contiguous."""
    f = spec["n_features"]
    X = rng.standard_normal((n, f))
    score = X[:, 0] + 0.4 * X[:, 1] + 0.3 * rng.standard_normal(n)
    if spec["objective"] == "binary":
        y = (score > 0).astype(np.float64)
    elif spec["objective"] == "multiclass":
        edges = np.quantile(score, np.linspace(0, 1, spec["num_class"] + 1))
        y = np.clip(np.searchsorted(edges, score) - 1, 0,
                    spec["num_class"] - 1).astype(np.float64)
    else:                                   # lambdarank relevance 0..3
        y = np.clip((score * 1.5 + 1.5), 0, 3).round().astype(np.float64)
    if spec.get("query"):
        qsz = spec["query_rows"]
        n_groups = int(math.ceil(n / qsz))
        qid = np.repeat(np.arange(next_qid, next_qid + n_groups), qsz)[:n]
        rows = np.column_stack([y, qid.astype(np.float64), X])
        return rows, next_qid + n_groups
    return np.column_stack([y, X]), next_qid


class StreamAppender(threading.Thread):
    """Grows the scenario's stream file on an interval, so the trainer's
    tail-append ingest and the rolling window both actually move."""

    def __init__(self, path: str, spec: Dict[str, Any], rows_per_append: int,
                 interval_s: float, seed: int, next_qid: int):
        super().__init__(name="sim-appender", daemon=True)
        self.path = path
        self.spec = spec
        self.rows_per_append = rows_per_append
        self.interval_s = interval_s
        self.rng = np.random.default_rng(seed)
        self.next_qid = next_qid
        self.appended = 0
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self.interval_s):
            rows, self.next_qid = gen_rows(self.spec, self.rows_per_append,
                                           self.rng, self.next_qid)
            with open(self.path, "a") as fh:
                np.savetxt(fh, rows, delimiter="\t", fmt="%.8g")
            self.appended += len(rows)

    def stop(self) -> None:
        self._halt.set()


# ---------------------------------------------------------------------------
# replica subprocess
# ---------------------------------------------------------------------------

def _make_shape(cfg_shape: Dict[str, Any], duration_s: float):
    from lightgbm_tpu.runtime.loadgen import TrafficShape
    kind = cfg_shape.get("kind", "diurnal")
    base = float(cfg_shape.get("base_rps", 30))
    peak = float(cfg_shape.get("peak_rps", 120))
    if kind == "diurnal":
        return TrafficShape.diurnal(base, peak, period_s=duration_s)
    if kind == "bursty":
        return TrafficShape.bursty(base, peak,
                                   period_s=max(duration_s / 4, 1.0),
                                   burst_len_s=max(duration_s / 16, 0.25))
    if kind == "step":
        third = duration_s / 3.0
        return TrafficShape.step([(third, base), (third, peak),
                                  (third, (base + peak) / 2)])
    raise ValueError("unknown shape kind %r" % kind)


class _FaultChurn(threading.Thread):
    """Seeded serving-fault windows: arm LGBM_TPU_FAULT for a step, then
    clear it for at least as long (the breaker needs quiet windows to
    run its recovery probe)."""

    def __init__(self, seed: int, step_s: float, ledger: List[str]):
        super().__init__(name="sim-fault-churn", daemon=True)
        self.rng = np.random.default_rng(seed)
        self.step_s = step_s
        self.ledger = ledger
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self.step_s):
            fault = FAULT_POOL[int(self.rng.integers(0, len(FAULT_POOL)))]
            if fault is None:
                continue
            os.environ["LGBM_TPU_FAULT"] = fault
            self.ledger.append(fault)
            if self._halt.wait(self.step_s):
                break
        os.environ.pop("LGBM_TPU_FAULT", None)

    def stop(self) -> None:
        self._halt.set()
        os.environ.pop("LGBM_TPU_FAULT", None)


def run_replica(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """One serving replica: runtime + policy + fault churn + verifying
    load generator.  Returns the machine-readable record (ledger +
    runtime stats + the replica's full metrics snapshot)."""
    from lightgbm_tpu.runtime.loadgen import (LoadGenerator, RequestClass,
                                              ResponseVerifier)
    from lightgbm_tpu.runtime.policy import AutoscaleShedPolicy
    from lightgbm_tpu.runtime.serving import ServingRuntime

    tracing.set_context("replica_%s" % cfg["scenario"])
    spec = SCENARIOS[cfg["scenario"]]
    rng = np.random.default_rng(cfg["seed"])
    probe = rng.standard_normal((64, spec["n_features"]))
    policy = AutoscaleShedPolicy(**cfg.get("policy", {}))
    rt = ServingRuntime(
        publish_dir=cfg["pub_dir"], params={"verbose": -1},
        max_queue=int(cfg.get("max_queue", 64)),
        batch_window_s=0.002,
        predict_deadline_s=float(cfg.get("predict_deadline_s", 0.5)),
        breaker_cooldown_s=0.3, poll_interval_s=0.05,
        priority_levels=3, quotas=cfg.get("quotas") or None,
        policy=policy)
    rt.start()
    deadline = time.monotonic() + 60
    while rt.generation() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    if rt.generation() is None:
        rt.stop()
        raise RuntimeError("replica: no generation appeared in %r"
                           % cfg["pub_dir"])

    classes = [RequestClass("gold", priority=0, weight=1.0, rows=2),
               RequestClass("silver", priority=1, weight=2.0, rows=4),
               RequestClass("bulk", priority=2, weight=3.0, rows=8)]
    shape = _make_shape(dict(spec["shape"], **cfg.get("shape", {})),
                        cfg["duration_s"])
    verifier = ResponseVerifier(probe, pub_dir=cfg["pub_dir"],
                                params={"verbose": -1})
    faults: List[str] = []
    churn = _FaultChurn(cfg["seed"] + 7,
                        step_s=float(cfg.get("fault_step_s", 1.0)),
                        ledger=faults)
    gen = LoadGenerator(rt, classes, shape, cfg["duration_s"], probe,
                        seed=cfg["seed"], verifier=verifier,
                        deadline_s=float(cfg.get("deadline_s", 2.0)),
                        # ISSUE 14: every 8th request is traced end to
                        # end; the ledger's `trace` section carries the
                        # stage-sum-vs-client-latency accounting
                        trace_every=int(cfg.get("trace_every", 8)))
    churn.start()
    try:
        ledger = gen.run()
    finally:
        churn.stop()
        churn.join(timeout=10)
        os.environ.pop("LGBM_TPU_FAULT", None)
    # post-churn settle so the breaker can demonstrate recovery
    time.sleep(0.3)
    stats = rt.stats()
    rt.stop()
    # flush this replica's flight recorder now (the atexit dump would
    # fire too, but an explicit flush cannot be lost to a hard exit)
    tracing.export_to_dir()
    return {
        "ledger": ledger,
        "stats": {k: stats[k] for k in
                  ("admitted", "completed", "rows_served", "batches_device",
                   "batches_host", "swaps", "degradations", "recoveries",
                   "rejected", "shed_active", "priority_levels")},
        "policy_decisions": policy.decisions,
        "faults_injected": faults,
        "final_generation": stats["generations"].get("default"),
        "snapshot": telemetry.snapshot("prod_sim_replica"),
    }


# ---------------------------------------------------------------------------
# registry scraping (the artifact's numbers)
# ---------------------------------------------------------------------------

def _hist_state(snapshots: List[Dict[str, Any]], name: str
                ) -> Dict[str, Any]:
    """Merged histogram state (summed counts over every replica and
    label set) for one metric family."""
    buckets = list(telemetry.METRIC_TABLE[name].get(
        "buckets", telemetry.LATENCY_BUCKETS_S))
    counts = [0] * len(buckets)
    total, cnt = 0.0, 0
    for snap in snapshots:
        for entry in snap.get("metrics", {}).get(name, {}).get("series", []):
            for i, v in enumerate(entry.get("counts", [])):
                counts[i] += v
            total += entry.get("sum", 0.0)
            cnt += entry.get("count", 0)
    return {"buckets": buckets, "counts": counts, "sum": total, "count": cnt}


def _sum_counter(snapshots: List[Dict[str, Any]], name: str,
                 by: Optional[str] = None) -> Dict[str, float]:
    """Summed counter values across replicas, keyed by label `by` (or
    "_total" when by is None)."""
    out: Dict[str, float] = {}
    for snap in snapshots:
        for entry in snap.get("metrics", {}).get(name, {}).get("series", []):
            key = entry.get("labels", {}).get(by, "_total") \
                if by else "_total"
            out[key] = out.get(key, 0.0) + entry.get("value", 0.0)
    return out


def _quantiles(state: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "p50": telemetry.quantile_from_state(state, 0.5),
        "p99": telemetry.quantile_from_state(state, 0.99),
        "count": state["count"],
        "mean": round(state["sum"] / state["count"], 6)
        if state["count"] else None,
    }


def collate_scenario(name: str, replica_records: List[Dict[str, Any]],
                     duration_s: float, trainer_info: Dict[str, Any]
                     ) -> Dict[str, Any]:
    """One scenario's artifact section, scraped from the replicas'
    registry snapshots."""
    snaps = [r["snapshot"] for r in replica_records]
    ledgers = [r["ledger"] for r in replica_records]
    n_rep = len(replica_records)
    rows = _sum_counter(snaps, "lgbm_serve_rows_total").get("_total", 0.0)
    offered = _sum_counter(snaps, "lgbm_loadgen_offered_total", by="cls")
    verify = _sum_counter(snaps, "lgbm_loadgen_verified_total", by="result")
    policy = _sum_counter(snaps, "lgbm_policy_decisions_total", by="action")

    # per-priority-class outcome matrix -> per-class shed ledger
    class_names = {0: "gold", 1: "silver", 2: "bulk"}
    by_class: Dict[str, Dict[str, float]] = {}
    for snap in snaps:
        fam = snap.get("metrics", {}).get("lgbm_serve_class_requests_total",
                                          {})
        for entry in fam.get("series", []):
            lab = entry.get("labels", {})
            cls = lab.get("cls", "?")
            slot = by_class.setdefault(cls, {})
            slot[lab.get("outcome", "?")] = \
                slot.get(lab.get("outcome", "?"), 0.0) + entry["value"]
    classes: Dict[str, Any] = {}
    for p, cname in class_names.items():
        outcomes = by_class.get("p%d" % p, {})
        done = outcomes.get("completed", 0.0)
        shed = sum(v for k, v in outcomes.items() if k != "completed")
        off = offered.get(cname, 0.0)
        classes[cname] = {
            "priority": p,
            "offered": int(off),
            "completed": int(done),
            "shed": int(shed),
            "shed_rate": round(shed / off, 4) if off else 0.0,
            "reasons": {k: int(v) for k, v in outcomes.items()
                        if k != "completed"},
        }

    faults = sum((r["faults_injected"] for r in replica_records), [])
    # per-request stage decomposition accounting (ISSUE 14): every
    # sampled request's queue/gather/device/drain sum must land within
    # one latency-bucket width of its client-observed latency
    trace_secs = [led.get("trace") for led in ledgers
                  if led.get("trace")]
    trace_sec = {
        "sampled": sum(t["sampled"] for t in trace_secs),
        "with_stages": sum(t["with_stages"] for t in trace_secs),
        "stage_sum_within_bucket": sum(t["stage_sum_within_bucket"]
                                       for t in trace_secs),
        "stage_sum_max_err_s": max(
            (t["stage_sum_max_err_s"] for t in trace_secs
             if t["stage_sum_max_err_s"] is not None), default=None),
        "ok": bool(trace_secs) and all(t["ok"] for t in trace_secs),
    } if trace_secs else None
    sec = {
        "objective": SCENARIOS[name]["objective"],
        "replicas": n_rep,
        "duration_s": duration_s,
        "shape": ledgers[0]["shape"] if ledgers else None,
        "offered_total": int(sum(offered.values())),
        "offered_rps_mean": round(sum(offered.values())
                                  / max(duration_s, 1e-9), 2),
        "latency_s": _quantiles(_hist_state(snaps,
                                            "lgbm_serve_latency_seconds")),
        "staleness_s": _quantiles(_hist_state(
            snaps, "lgbm_serve_staleness_seconds")),
        "capacity_rows_per_sec_per_replica": round(
            rows / max(duration_s, 1e-9) / max(n_rep, 1), 2),
        "classes": classes,
        "verification": {k: int(v) for k, v in verify.items()},
        "non_machine_readable_rejections": sum(
            led["non_machine_readable_rejections"] for led in ledgers),
        "hard_errors": sum((led["hard_errors"] for led in ledgers), [])[:10],
        "served_by": {
            "device": sum(led["served_by"].get("device", 0)
                          for led in ledgers),
            "host": sum(led["served_by"].get("host", 0) for led in ledgers)},
        "degradations": sum(r["stats"]["degradations"]
                            for r in replica_records),
        "recoveries": sum(r["stats"]["recoveries"] for r in replica_records),
        "swaps": sum(r["stats"]["swaps"] for r in replica_records),
        "policy_decisions": {k: int(v) for k, v in policy.items()},
        "faults_injected": faults,
        "final_generations": [r["final_generation"]
                              for r in replica_records],
        "trainer": trainer_info,
    }
    # every completed response must have produced a verdict — a silent
    # verification undercount (e.g. a dead client-pool thread) fails the
    # scenario even when the verdicts that DID land are all clean
    sec["loadgen_completed"] = sum(
        sum(c["completed"] for c in led["classes"].values())
        for led in ledgers)
    sec["verified_total"] = int(sum(verify.values()))
    if trace_sec is not None:
        sec["trace"] = trace_sec
    wrong = sec["verification"].get("wrong_generation", 0) \
        + sec["verification"].get("mismatch", 0) \
        + sec["verification"].get("unverifiable", 0)
    sec["ok"] = bool(
        sec["verification"].get("ok", 0) > 0
        and sec["verified_total"] == sec["loadgen_completed"]
        and wrong == 0
        and not sec["hard_errors"]
        and sec["non_machine_readable_rejections"] == 0
        and trainer_info.get("generations", 0) >= 2
        and min(g or 0 for g in sec["final_generations"]) >= 2
        # churn must actually have pushed traffic onto the host path
        and (not faults or sec["served_by"]["host"] > 0)
        # sampled tracing ran: every stage sum within its bucket width
        and (trace_sec is None or trace_sec["ok"]))
    return sec


# ---------------------------------------------------------------------------
# merged-trace verification (the TRACE_r* artifact's machine gates)
# ---------------------------------------------------------------------------

def verify_merged_trace(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Machine gates over one merged timeline (ISSUE 14 acceptance):

    * ``request_chain_ok`` — some trace id carries a loadgen client
      span AND the server-side device + drain stage slices (the
      loadgen → serving → device batch → drain chain);
    * ``publish_link_ok`` — some publish flow arrow starts in one
      process (the trainer) and ends in ANOTHER (a replica's swap-in):
      the trainer cycle → publish → subscriber link;
    * ``cycle_spans`` / ``serve_batches`` — both sides of the system
      actually recorded their timelines.
    """
    evs = doc.get("traceEvents", [])
    by_trace: Dict[str, set] = {}
    for e in evs:
        t = (e.get("args") or {}).get("trace")
        if t:
            by_trace.setdefault(t, set()).add(str(e.get("name")))
    request_chain = sum(
        1 for names in by_trace.values()
        if {"req/device", "req/drain"} <= names
        and any(n.startswith("client request") for n in names))
    s_pids = {e.get("id"): e.get("pid") for e in evs if e.get("ph") == "s"}
    cross_links = sum(1 for e in evs if e.get("ph") == "f"
                      and e.get("id") in s_pids
                      and e.get("pid") != s_pids[e.get("id")])
    cycles = sum(1 for e in evs
                 if str(e.get("name", "")).startswith("cycle "))
    batches = sum(1 for e in evs if e.get("name") == "serve batch")
    rec = {
        "events": len([e for e in evs if e.get("ph") != "M"]),
        "processes": len({e.get("pid") for e in evs}),
        "request_chains": request_chain,
        "request_chain_ok": request_chain > 0,
        "publish_cross_process_links": cross_links,
        "publish_link_ok": cross_links > 0,
        "cycle_spans": cycles,
        "serve_batches": batches,
    }
    rec["ok"] = bool(rec["request_chain_ok"] and rec["publish_link_ok"]
                     and cycles > 0 and batches > 0)
    return rec


# ---------------------------------------------------------------------------
# one scenario end to end
# ---------------------------------------------------------------------------

def run_scenario(name: str, workdir: str, replicas: int = 2,
                 duration_s: float = 20.0, interval_s: float = 3.0,
                 seed: int = 11, initial_rows: int = 1200,
                 window_rows: int = 2000, log=print) -> Dict[str, Any]:
    spec = SCENARIOS[name]
    sdir = os.path.join(workdir, name)
    os.makedirs(sdir, exist_ok=True)
    pub_dir = os.path.join(sdir, "pub")
    data_path = os.path.join(sdir, "stream.tsv")

    rng = np.random.default_rng(seed)
    rows, next_qid = gen_rows(spec, initial_rows, rng)
    np.savetxt(data_path, rows, delimiter="\t", fmt="%.8g")

    env = dict(os.environ)
    env.pop("LGBM_TPU_FAULT", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # one persistent compile cache for the whole fleet (ISSUE 15): the
    # trainer and every replica share compiled programs instead of each
    # paying the cold compile (the fingerprinted subdir keeps it safe)
    env.setdefault(warmup.CACHE_ENV, os.path.join(workdir, "compile_cache"))
    # every process of the fleet self-collects its trace ring here
    # (ISSUE 14): the trainer's cycles + publishes, each replica's
    # requests/batches/swaps — merged below into ONE timeline
    traces_dir = os.path.join(sdir, "traces")
    env[tracing.TRACE_DIR_ENV] = traces_dir
    # one causal umbrella for the scenario's whole fleet: every child's
    # root spans parent under this context (the env-seed passthrough)
    env[tracing.TRACEPARENT_ENV] = tracing.make_traceparent(
        tracing.new_trace_id(), tracing.new_span_id())

    # -- the continuous trainer: its own process, publishing forever ------
    train_args = ["task=train_online", "data=" + data_path,
                  "output_model=" + os.path.join(sdir, "model.txt"),
                  "publish_dir=" + pub_dir,
                  "online_interval=%g" % interval_s,
                  "online_cycles=0", "online_rounds=3",
                  "online_window_rows=%d" % window_rows,
                  # retention must cover the whole run: the verifier
                  # re-reads any generation a response names
                  "publish_retention=1000", "publish_grace=600",
                  "verbose=-1"]
    if spec.get("query"):
        train_args.append("query_column=0")
    for k, v in spec["train_params"].items():
        train_args.append("%s=%s" % (k, v))
    t_log = open(os.path.join(sdir, "trainer.log"), "w")
    trainer = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu"] + train_args,
        cwd=sdir, env=env, stdout=t_log, stderr=subprocess.STDOUT)

    appender = StreamAppender(data_path, spec,
                              rows_per_append=max(window_rows // 8, 100),
                              interval_s=max(interval_s / 2, 0.5),
                              seed=seed + 1, next_qid=next_qid)
    appender.start()

    try:
        # wait for generation 1 before pointing replicas at the dir
        sub = publish.ModelSubscriber(pub_dir, attempts=1)
        deadline = time.monotonic() + max(duration_s * 3, 120)
        while sub.resolve_once() is None:
            if trainer.poll() is not None:
                raise RuntimeError(
                    "trainer died before the first publish (see %s)"
                    % t_log.name)
            if time.monotonic() > deadline:
                raise RuntimeError("no generation published in time")
            time.sleep(0.1)

        # -- the replica fleet -------------------------------------------
        procs = []
        for r in range(replicas):
            cfg = {"scenario": name, "pub_dir": pub_dir,
                   "duration_s": duration_s, "seed": seed + 100 * (r + 1),
                   "quotas": {"default": 0.75},
                   "policy": {"high_watermark": 0.6, "low_watermark": 0.2,
                              "patience": 3, "interval_s": 0.05},
                   "fault_step_s": max(duration_s / 12, 0.5)}
            cfg_path = os.path.join(sdir, "replica%d.json" % r)
            out_path = os.path.join(sdir, "replica%d.out.json" % r)
            with open(cfg_path, "w") as fh:
                json.dump(cfg, fh)
            rlog = open(os.path.join(sdir, "replica%d.log" % r), "w")
            procs.append((subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--replica",
                 cfg_path, out_path],
                cwd=sdir, env=env, stdout=rlog, stderr=subprocess.STDOUT),
                out_path, rlog))
        records = []
        for proc, out_path, rlog in procs:
            rc = proc.wait(timeout=duration_s * 6 + 180)
            rlog.close()
            if rc != 0:
                with open(rlog.name) as fh:
                    raise RuntimeError("replica failed (rc=%d): %s"
                                       % (rc, fh.read()[-2000:]))
            with open(out_path) as fh:
                records.append(json.load(fh))
    finally:
        appender.stop()
        trainer.send_signal(signal.SIGTERM)
        try:
            trainer.wait(timeout=60)
        except subprocess.TimeoutExpired:
            trainer.kill()
        t_log.close()

    latest = publish.ModelPublisher(pub_dir).latest_valid()
    trainer_info = {
        "generations": latest.generation if latest else 0,
        "interval_s": interval_s,
        "rows_appended": appender.appended,
        "exit_rc": trainer.returncode,
    }
    sec = collate_scenario(name, records, duration_s, trainer_info)
    # fuse the fleet's per-process trace rings into ONE timeline and
    # gate it: the request chain and the publish→subscriber link must
    # both be visible in the merged view (ISSUE 14 acceptance)
    trace_files = sorted(glob.glob(os.path.join(traces_dir, "trace_*.json")))
    if trace_files:
        merged_path = os.path.join(sdir, "trace_merged.json")
        merged = tracing.merge_traces(trace_files, out_path=merged_path,
                                      max_events=TRACE_MAX_EVENTS)
        sec["trace_merged"] = dict(verify_merged_trace(merged),
                                   files=len(trace_files),
                                   file=merged_path)
        sec["ok"] = bool(sec["ok"] and sec["trace_merged"]["ok"])
    log("prod_sim[%s]: ok=%s offered=%d p99=%.3fs staleness_p50=%.1fs "
        "capacity=%.0f rows/s/replica sheds=%s gens=%s"
        % (name, sec["ok"], sec["offered_total"],
           sec["latency_s"]["p99"] or -1, sec["staleness_s"]["p50"] or -1,
           sec["capacity_rows_per_sec_per_replica"],
           {c: v["shed"] for c, v in sec["classes"].items()},
           trainer_info["generations"]))
    return sec


def run_sim(workdir: str, scenarios: Optional[List[str]] = None,
            replicas: int = 2, duration_s: float = 20.0,
            interval_s: float = 3.0, seed: int = 11,
            log=print) -> Dict[str, Any]:
    t0 = time.monotonic()
    out: Dict[str, Any] = {
        "artifact": "SIM_r11",
        "schema_version": SCHEMA_VERSION,
        "t_start": resilience.wallclock(),
        "replicas": replicas,
        "duration_s": duration_s,
        "seed": seed,
        "scenarios": {},
    }
    for name in (scenarios or list(SCENARIOS)):
        out["scenarios"][name] = run_scenario(
            name, workdir, replicas=replicas, duration_s=duration_s,
            interval_s=interval_s, seed=seed, log=log)
    out["elapsed_s"] = round(time.monotonic() - t0, 1)
    out["ok"] = bool(out["scenarios"]) and all(
        s["ok"] for s in out["scenarios"].values())
    return out


# ---------------------------------------------------------------------------
# elastic-fleet scenarios (ISSUE 17): SLO-driven autoscaling at 10x the
# r11 offered load, with a model-zoo tenant mix and fault churn killing
# replicas mid-scale-up
# ---------------------------------------------------------------------------

#: r11's committed offered_rps_mean for the binary scenario — the
#: baseline the >=10x fleet-load gate measures against (SIM_r11.json)
R11_OFFERED_RPS_MEAN = 149.75

#: registered model-zoo tenants per replica (bounded residency holds
#: only `max_resident` of them loaded; the rest page in on demand)
FLEET_TENANTS = 120

#: tenants that actually receive bulk traffic — more than
#: `max_resident` minus the default lineage, so LRU page-in/evict churn
#: runs for the whole scenario
FLEET_HOT_TENANTS = 8

FLEET_SCENARIOS: Dict[str, Dict[str, Any]] = {
    "fleet_diurnal": {
        "objective": "binary", "n_features": 8,
        "shape": {"kind": "diurnal", "base_rps": 700, "peak_rps": 2600},
        # the FIRST scale-up dies during its prewarm, before /healthz
        # ever answers ready — the relaunch path on the most expensive
        # death window (armed for every replica; only the matching
        # fleet spawn ordinal dies)
        "fault": "die_at_spawn:2",
    },
    "fleet_bursty": {
        "objective": "binary", "n_features": 8,
        # base leaves ONE replica slack between bursts (pressure breaks
        # per burst instead of fusing bursts into one long episode);
        # the burst itself saturates the whole box
        "shape": {"kind": "bursty", "base_rps": 800, "peak_rps": 3800},
        # the SECOND scale-up dies mid-prewarm: bursty's first episode
        # rides on one base replica, so killing spawn 2 would fuse the
        # burst and the relaunch into one fault-stretched episode the
        # reaction gate can't attribute to the autoscaler
        "fault": "die_at_spawn:3",
    },
}


def _train_fleet_model(workdir: str, spec: Dict[str, Any],
                       seed: int) -> str:
    """One small real booster, trained once per sim run — every zoo
    tenant publishes the SAME text, so the byte-verifier's
    generation->reference map stays unambiguous across tenants."""
    from lightgbm_tpu.basic import Booster, Dataset
    path = os.path.join(workdir, "fleet_model.txt")
    if os.path.exists(path):
        with open(path) as fh:
            return fh.read()
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((600, spec["n_features"]))
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(np.float64)
    ds = Dataset(X, label=y, params={"verbose": -1})
    bst = Booster(params={"objective": "binary", "num_leaves": 15,
                          "verbose": -1}, train_set=ds)
    for _ in range(3):
        bst.update()
    text = bst.model_to_string()
    resilience.atomic_write(path, text)
    return text


def _publish_zoo(sdir: str, text: str) -> Dict[str, str]:
    """default + FLEET_TENANTS published model dirs (generation 1
    each); returns the model_id -> dir map the replica spec registers."""
    models: Dict[str, str] = {}
    for mid in ["default"] + ["t%03d" % i for i in range(FLEET_TENANTS)]:
        d = os.path.join(sdir, "zoo", mid)
        publish.ModelPublisher(d).publish(text)
        models[mid] = d
    return models


class _ReplicaKiller(threading.Thread):
    """SIGKILL one READY replica partway through the run — the abrupt
    fleet-level death (no drain, no final snapshot scrape) the
    controller must absorb with a relaunch."""

    def __init__(self, controller, at_s: float, ledger: List[str]):
        super().__init__(name="sim-replica-killer", daemon=True)
        self.controller = controller
        self.at_s = at_s
        self.ledger = ledger
        self._halt = threading.Event()

    def run(self) -> None:
        if self._halt.wait(self.at_s):
            return
        with self.controller._lock:         # noqa: SLF001 — sim harness
            ready = [h for h in self.controller.replicas
                     if h.ready and not h.retiring]
            if not ready:
                return
            victim = max(ready, key=lambda h: h.spawned_mono)
            try:
                victim.proc.kill()
                self.ledger.append("sigkill:%s" % victim.name)
            except OSError:
                pass

    def stop(self) -> None:
        self._halt.set()


def collate_fleet_scenario(name: str, ledger: Dict[str, Any],
                           fleet: Dict[str, Any],
                           snaps: List[Dict[str, Any]],
                           duration_s: float) -> Dict[str, Any]:
    """One fleet scenario's artifact section: loadgen ledger (client
    side — completions and byte-verification verdicts) + controller
    report (scale events, reactions, replica-seconds) + the replicas'
    last scraped registry snapshots (latency/staleness/residency)."""
    spec = FLEET_SCENARIOS[name]
    verification = {k: int(v) for k, v in
                    (ledger.get("verification") or {}).items()}
    completed = sum(c["completed"] for c in ledger["classes"].values())
    verified = int(sum(verification.values()))
    ok_verified = verification.get("ok", 0)
    residency = _sum_counter(snaps, "lgbm_serve_residency_events_total",
                             by="event")
    rows = _sum_counter(snaps, "lgbm_serve_rows_total").get("_total", 0.0)
    replica_s = float(fleet.get("replica_seconds") or 0.0)
    reactions = list(fleet.get("reactions_s") or [])
    reaction_max = max(reactions) if reactions else None
    shed_on_decisions = [d for d in (fleet.get("events") or [])
                         if d["action"] == "shed_on"]
    # shed is last resort: every shed_on grant must land while the
    # policy target is pinned at max_replicas
    tl = fleet.get("timeline") or []
    max_replicas = int(fleet["policy"]["max_replicas"])

    def _target_at(t_s: float) -> Optional[int]:
        at = None
        for row in tl:
            if row["t_s"] <= t_s:
                at = row["target"]
        return at

    shed_only_at_max = all(
        (_target_at(e["t_s"]) or max_replicas) >= max_replicas
        for e in shed_on_decisions)
    spawn_to_ready = [e["spawn_to_ready_s"]
                      for e in (fleet.get("events") or [])
                      if e["action"] == "ready"]
    offered_x = (ledger["offered_rps_mean"] / R11_OFFERED_RPS_MEAN
                 if R11_OFFERED_RPS_MEAN else 0.0)
    wrong = verification.get("wrong_generation", 0) \
        + verification.get("mismatch", 0) \
        + verification.get("unverifiable", 0)
    sec: Dict[str, Any] = {
        "objective": spec["objective"],
        "replicas": max_replicas,
        "duration_s": duration_s,
        "shape": ledger["shape"],
        "offered_total": int(ledger["offered_total"]),
        "offered_rps_mean": ledger["offered_rps_mean"],
        "max_lag_s": ledger["max_lag_s"],
        "latency_s": _quantiles(_hist_state(snaps,
                                            "lgbm_serve_latency_seconds")),
        "staleness_s": _quantiles(_hist_state(
            snaps, "lgbm_serve_staleness_seconds")),
        "capacity_rows_per_sec_per_replica": round(
            rows / max(replica_s, 1e-9), 2),
        "classes": ledger["classes"],
        "verification": verification,
        "non_machine_readable_rejections":
            ledger["non_machine_readable_rejections"],
        "hard_errors": ledger["hard_errors"][:10],
        "served_by": dict(ledger["served_by"]),
        "loadgen_completed": completed,
        "verified_total": verified,
        "fleet": {
            "min_replicas": int(fleet["policy"]["min_replicas"]),
            "max_replicas": max_replicas,
            "scale_ups": int(fleet["scale_ups"]),
            "scale_downs": int(fleet["scale_downs"]),
            "relaunches": int(fleet["relaunches"]),
            "replica_seconds": round(replica_s, 3),
            "replica_seconds_per_million_verified": round(
                replica_s * 1e6 / ok_verified, 1) if ok_verified else None,
            "reactions_s": reactions,
            "scale_up_reaction_s_max": reaction_max,
            "spawn_to_ready_s": spawn_to_ready,
            "offered_x_r11": round(offered_x, 2),
            "shed_only_at_max": bool(shed_only_at_max),
            "shed_grants": len(shed_on_decisions),
            "faults_injected": fleet.get("faults_injected", []),
            "residency": {k: int(v) for k, v in residency.items()},
            "events": [e for e in (fleet.get("events") or [])
                       if e["action"] != "ready"],
            "timeline": tl,
        },
    }
    sec["ok"] = bool(
        ok_verified > 0
        and wrong == 0
        and verified == completed
        and not sec["hard_errors"]
        and sec["non_machine_readable_rejections"] == 0
        and sec["fleet"]["scale_ups"] >= 2
        and sec["fleet"]["scale_downs"] >= 1
        and sec["fleet"]["relaunches"] >= 1
        and (reaction_max is not None and reaction_max <= 15.0)
        and shed_only_at_max
        and offered_x >= 10.0)
    return sec


def run_fleet_scenario(name: str, workdir: str, duration_s: float = 40.0,
                       seed: int = 17, max_replicas: int = 4,
                       load_scale: float = 1.0,
                       log=print) -> Dict[str, Any]:
    """One elastic-fleet scenario end to end: zoo publish -> controller
    (min 1, max `max_replicas` replicas) -> verified open-loop load at
    >=10x r11 through the binary wire -> fault churn (die_at_spawn on
    the first scale-up + SIGKILL of a ready replica) -> collate."""
    from lightgbm_tpu.runtime.fleet import FleetClient, FleetController
    from lightgbm_tpu.runtime.loadgen import (LoadGenerator, RequestClass,
                                              ResponseVerifier)
    from lightgbm_tpu.runtime.policy import FleetScalePolicy

    spec = FLEET_SCENARIOS[name]
    sdir = os.path.join(workdir, name)
    os.makedirs(sdir, exist_ok=True)
    text = _train_fleet_model(workdir, spec, seed)
    models = _publish_zoo(sdir, text)

    # one persistent compile cache for the whole fleet (ISSUE 15): the
    # first replica pays the compile, every later spawn starts warm —
    # the seam that makes spawn-to-ready ~2 s
    os.environ.setdefault(warmup.CACHE_ENV,
                          os.path.join(workdir, "compile_cache"))
    replica_spec = {
        "models": models,
        "params": {"verbose": -1},
        "response_dtype": "float32",
        "max_queue": 256,
        # the per-replica capacity knob: 8 rows per device dispatch
        # bounds one replica's throughput, so added replicas add real
        # capacity (and the autoscaler has something to scale)
        "max_batch_rows": 8,
        "batch_window_s": 0.002,
        "predict_deadline_s": 5.0,
        "poll_interval_s": 0.1,
        "priority_levels": 3,
        "quotas": {"default": 0.6, "*": 0.2},
        "max_resident": 6,
        "shed_policy": True,
        "shed_high": 0.85, "shed_low": 0.5, "shed_patience": 4,
    }
    # high watermark sits BELOW the p2 class reservation cutoff (bulk
    # sheds at depth_frac 1/3): the fleet scales before the lowest
    # class starts shedding, and sheds only once replicas are maxed
    # the p99 SLO budgets one model-zoo page-in (the bulk tenants LRU-
    # cycle through max_resident slots all run, so the steady-state p99
    # rides the page-in wait, not pure queueing — an SLO below that
    # floor would read permanent pressure no replica count can clear)
    # the low watermark sits ABOVE the page-in depth floor (~0.10 —
    # queued requests waiting on zoo page-ins keep that much depth at
    # ANY replica count), or the trough would never read as slack
    policy = FleetScalePolicy(
        min_replicas=1, max_replicas=max_replicas,
        slo_p99_s=0.3, high_watermark=0.25, low_watermark=0.15,
        patience=3, scale_down_patience=6, interval_s=0.5)
    ctl = FleetController(
        os.path.join(sdir, "fleet"), replica_spec, policy=policy,
        interval_s=0.5, spawn_grace_s=60.0,
        env={"LGBM_TPU_FAULT": spec["fault"], "JAX_PLATFORMS": "cpu"})
    faults: List[str] = [spec["fault"]]
    ctl.start()
    ctl.wait_ready(1, timeout=120)

    rng = np.random.default_rng(seed)
    probe = rng.standard_normal((64, spec["n_features"]))
    shape_cfg = dict(spec["shape"])
    for k in ("base_rps", "peak_rps"):
        shape_cfg[k] = shape_cfg[k] * load_scale
    shape = _make_shape(shape_cfg, duration_s)
    hot = ["t%03d" % i for i in range(FLEET_HOT_TENANTS)]
    classes = [RequestClass("gold", priority=0, weight=1.0, rows=1),
               RequestClass("silver", priority=1, weight=2.0, rows=2)]
    classes += [RequestClass("bulk-%s" % mid, priority=2, model_id=mid,
                             weight=3.0 / len(hot), rows=4)
                for mid in hot]
    # wire responses are float32 — verify against the SAME
    # deterministic narrowing of the exact f64 reference
    verifier = ResponseVerifier(probe, pub_dir=models["default"],
                                params={"verbose": -1},
                                value_dtype=np.float32)
    cli = FleetClient(ctl, workers=96, predict_deadline_s=5.0,
                      request_timeout_s=10.0)
    gen = LoadGenerator(cli, classes, shape, duration_s, probe,
                        seed=seed, verifier=verifier, deadline_s=2.0,
                        waiters=16, trace_every=0)
    killer = _ReplicaKiller(ctl, at_s=duration_s * 0.55, ledger=faults)
    killer.start()
    try:
        ledger = gen.run()
    finally:
        killer.stop()
        cli.close()
    # cooldown: zero offered load while the controller keeps ticking —
    # the contraction half of elasticity (slack streak -> shed grant
    # returned -> scale-downs) needs a guaranteed trough to land in,
    # and the timeline should show the fleet actually letting go
    time.sleep(10.0)
    # final scrape before teardown so the artifact's histograms carry
    # the whole run (dead replicas keep their LAST scraped snapshot)
    snaps = []
    with ctl._lock:                          # noqa: SLF001 — sim harness
        for h in ctl.replicas + ctl.retired:
            if h.last_snapshot is not None:
                snaps.append(h.last_snapshot)
    fleet = ctl.stop()
    fleet["faults_injected"] = faults
    sec = collate_fleet_scenario(name, ledger, fleet, snaps, duration_s)
    fl = sec["fleet"]
    log("prod_sim[%s]: ok=%s offered=%.0f rps (%.1fx r11) ups=%d "
        "downs=%d relaunches=%d reaction_max=%s spawn_ready=%s "
        "rs/1Mverified=%s resident_events=%s"
        % (name, sec["ok"], sec["offered_rps_mean"], fl["offered_x_r11"],
           fl["scale_ups"], fl["scale_downs"], fl["relaunches"],
           fl["scale_up_reaction_s_max"],
           ["%.2f" % s for s in fl["spawn_to_ready_s"]],
           fl["replica_seconds_per_million_verified"],
           fl["residency"]))
    return sec


def run_fleet_sim(workdir: str, scenarios: Optional[List[str]] = None,
                  duration_s: float = 40.0, seed: int = 17,
                  max_replicas: int = 4, load_scale: float = 1.0,
                  log=print) -> Dict[str, Any]:
    t0 = time.monotonic()
    out: Dict[str, Any] = {
        "artifact": "SIM_r17",
        "schema_version": SCHEMA_VERSION,
        "t_start": resilience.wallclock(),
        "replicas": max_replicas,
        "duration_s": duration_s,
        "seed": seed,
        "r11_offered_rps_mean": R11_OFFERED_RPS_MEAN,
        "scenarios": {},
    }
    for name in (scenarios or list(FLEET_SCENARIOS)):
        out["scenarios"][name] = run_fleet_scenario(
            name, workdir, duration_s=duration_s, seed=seed,
            max_replicas=max_replicas, load_scale=load_scale, log=log)
    out["elapsed_s"] = round(time.monotonic() - t0, 1)
    out["ok"] = bool(out["scenarios"]) and all(
        s["ok"] for s in out["scenarios"].values())
    return out


def main(argv: List[str]) -> int:
    if len(argv) > 1 and argv[1] == "--replica":
        with open(argv[2]) as fh:
            cfg = json.load(fh)
        rec = run_replica(cfg)
        resilience.atomic_write(argv[3], json.dumps(rec))
        return 0
    import tempfile
    if "--fleet" in argv:
        # ISSUE 17: the elastic-fleet sim — autoscaling controller +
        # model-zoo replicas at >=10x the r11 offered load
        args = [a for a in argv[1:] if not a.startswith("--")]
        artifact = args[0] if args else os.path.join(REPO, "SIM_r17.json")
        quick = "--quick" in argv
        seed = int(os.environ.get("PROD_SIM_SEED", "17"))
        duration = float(os.environ.get("PROD_SIM_DURATION",
                                        "12" if quick else "40"))
        load_scale = float(os.environ.get("PROD_SIM_LOAD_SCALE", "1.0"))
        scenarios = ["fleet_diurnal"] if quick else None
        with tempfile.TemporaryDirectory(prefix="lgbm_fleet_sim_") as wd:
            rec = run_fleet_sim(wd, scenarios=scenarios,
                                duration_s=duration, seed=seed,
                                load_scale=load_scale)
        from helper.bench_history import validate_sim_artifact
        problems = validate_sim_artifact(rec)
        if problems:
            print("prod_sim: INVALID artifact: %s" % "; ".join(problems))
            return 2
        resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
        print("prod_sim: ok=%s scenarios=%s elapsed=%.0fs artifact=%s"
              % (rec["ok"], ",".join(rec["scenarios"]), rec["elapsed_s"],
                 artifact), flush=True)
        return 0 if rec["ok"] else 1
    quick = "--quick" in argv
    args = [a for a in argv[1:] if not a.startswith("--")]
    artifact = args[0] if args else os.path.join(REPO, "SIM_r11.json")
    seed = int(os.environ.get("PROD_SIM_SEED", "11"))
    replicas = int(os.environ.get("PROD_SIM_REPLICAS", "2"))
    duration = float(os.environ.get("PROD_SIM_DURATION",
                                    "8" if quick else "20"))
    trace_out = os.environ.get("PROD_SIM_TRACE_OUT")
    with tempfile.TemporaryDirectory(prefix="lgbm_prod_sim_") as wd:
        rec = run_sim(wd, scenarios=["binary"] if quick else None,
                      replicas=replicas, duration_s=duration,
                      interval_s=2.0 if quick else 3.0, seed=seed)
        # the committed trace artifact (ISSUE 14): ONE merged Perfetto
        # timeline (loadgen → serving → device → drain chain + trainer
        # cycle → publish → subscriber link) with its machine gates —
        # built while the workdir still holds the per-process rings
        if trace_out:
            merged_doc = None
            gates = {}
            for name, sec in rec["scenarios"].items():
                tm = sec.get("trace_merged")
                if tm is None:
                    continue
                gates[name] = {k: v for k, v in tm.items() if k != "file"}
                if merged_doc is None and os.path.exists(tm["file"]):
                    with open(tm["file"]) as fh:
                        merged_doc = json.load(fh)
            trace_art = {
                "artifact": os.path.splitext(
                    os.path.basename(trace_out))[0],
                "schema_version": TRACE_SCHEMA_VERSION,
                "replicas": replicas,
                "seed": seed,
                "gates": gates,
                "stage_sum": {name: sec.get("trace")
                              for name, sec in rec["scenarios"].items()},
                "ok": bool(gates) and all(g["ok"] for g in gates.values())
                and all((sec.get("trace") or {}).get("ok")
                        for sec in rec["scenarios"].values()),
                "trace": merged_doc,
            }
            resilience.atomic_write(trace_out,
                                    json.dumps(trace_art) + "\n")
            print("prod_sim: trace artifact ok=%s -> %s (%d events, "
                  "%d processes)"
                  % (trace_art["ok"], trace_out,
                     (merged_doc or {}).get("otherData", {})
                     .get("events", 0),
                     max((g.get("processes", 0)
                          for g in gates.values()), default=0)),
                  flush=True)
        for sec in rec["scenarios"].values():
            # the merged-trace file lives in the (deleted) workdir; keep
            # the gates, drop the dangling path from the SIM artifact
            if "trace_merged" in sec:
                sec["trace_merged"].pop("file", None)
    # a malformed artifact must fail loudly, not land in the repo
    from helper.bench_history import validate_sim_artifact
    problems = validate_sim_artifact(rec)
    if problems:
        print("prod_sim: INVALID artifact: %s" % "; ".join(problems))
        return 2
    resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
    print("prod_sim: ok=%s scenarios=%s elapsed=%.0fs artifact=%s"
          % (rec["ok"], ",".join(rec["scenarios"]), rec["elapsed_s"],
             artifact), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
