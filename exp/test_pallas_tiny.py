import sys, time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

print("backend:", jax.default_backend(), flush=True)

# 1. trivial kernel
def k1(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0

x = jnp.ones((256, 128), jnp.float32)
t0 = time.time()
out = pl.pallas_call(
    k1, out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM))(x)
jax.block_until_ready(out)
print("trivial kernel ok, %.1fs, sum=%s" % (time.time() - t0, out.sum()), flush=True)

# 2. scalar prefetch + manual DMA at dynamic offset + dynamic fori bound
C = 512
N = 2 ** 15
P = 32
payload = jnp.asarray(np.random.default_rng(0).standard_normal((N, P)), jnp.float32)

def k2(scalars_ref, hbm_ref, o_ref, chunk, sem):
    start = scalars_ref[0]
    nchunks = scalars_ref[1]
    o_ref[:] = jnp.zeros_like(o_ref)

    def body(k, _):
        dma = pltpu.make_async_copy(
            hbm_ref.at[pl.ds(start + k * C, C), :], chunk, sem)
        dma.start()
        dma.wait()
        o_ref[:] += jnp.sum(chunk[:], axis=0, keepdims=True)
        return 0

    lax.fori_loop(0, nchunks, body, 0)

t0 = time.time()
fn = jax.jit(lambda p, s, n: pl.pallas_call(
    k2,
    grid_spec=pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((C, P), jnp.float32),
                        pltpu.SemaphoreType.DMA(())]),
    out_shape=jax.ShapeDtypeStruct((1, P), jnp.float32),
)(jnp.stack([s, n]).astype(jnp.int32), p))
out = fn(payload, jnp.int32(1024), jnp.int32(8))
jax.block_until_ready(out)
ref = np.asarray(payload)[1024:1024 + 8 * C].sum(axis=0)
print("dma kernel ok, %.1fs, err=%.2e" % (
    time.time() - t0, np.abs(np.asarray(out)[0] - ref).max()), flush=True)
