import sys, time; sys.path.insert(0, '/root/repo')
import numpy as np, jax, jax.numpy as jnp
import lightgbm_tpu as lgb

for n in (4096, 65536, 500_000):
    rng = np.random.default_rng(7)
    X = rng.standard_normal((n, 28)).astype(np.float32)
    y = (X[:, 0] + 0.5*X[:, 1] + rng.standard_normal(n)*0.5 > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "learning_rate": 0.1, "verbose": -1, "min_data_in_leaf": 2}
    bst = lgb.Booster(params, lgb.Dataset(X, label=y))
    for _ in range(3):
        bst.update()
    eng = bst._engine
    fs = eng._fast
    fmask = eng._feature_sample()
    def grow():
        global out
        out, fs.payload, fs.aux = fs.grower(fs.payload, fs.aux, fmask)
    grow()
    t0 = time.perf_counter()
    for _ in range(3): grow()
    jax.block_until_ready(fs.payload)
    dt = (time.perf_counter() - t0) / 3 * 1e3
    print("n=%7d  grow: %7.2f ms   (leaves grown: %d)" % (n, dt, int(np.asarray(out["num_leaves"]))), flush=True)

# --- fixed-cost dissection: per-split device overhead vs num_leaves.
# grow() is one jitted program; the slope of time vs (num_leaves-1) at tiny
# N isolates the per-split cost of everything that is NOT row work
# (find_best_split scans, pool bookkeeping, kernel sequencing).  Fetch a
# scalar per rep — the tunnel's block_until_ready can return early.
import time as _t
n = 4096
rng = np.random.default_rng(7)
X = rng.standard_normal((n, 28)).astype(np.float32)
y = (X[:, 0] + 0.5*X[:, 1] + rng.standard_normal(n)*0.5 > 0).astype(np.float64)
for leaves in (2, 15, 63, 255):
    params = {"objective": "binary", "num_leaves": leaves, "max_bin": 255,
              "learning_rate": 0.1, "verbose": -1, "min_data_in_leaf": 2}
    bst = lgb.Booster(params, lgb.Dataset(X, label=y))
    for _ in range(2):
        bst.update()
    eng = bst._engine
    fs = eng._fast
    fmask = eng._feature_sample()
    def grow_fetch(i):
        out, fs.payload, fs.aux = fs.grower(fs.payload, fs.aux, fmask)
        return int(np.asarray(out["num_leaves"]))
    grow_fetch(0)
    ts = []
    for i in range(5):
        t0 = _t.perf_counter()
        nl = grow_fetch(i)
        ts.append(_t.perf_counter() - t0)
    med = sorted(ts)[2]
    print("leaves=%4d  grow: %7.2f ms  (%.3f ms/split)"
          % (leaves, med * 1e3, med * 1e3 / max(leaves - 1, 1)), flush=True)
