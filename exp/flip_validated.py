#!/usr/bin/env python
"""Flip the hardware-validation flags after a GREEN smoke run.

Usage: python exp/flip_validated.py acc [roll] [repeat]

Only run this after `exp/smoke_tpu_kernels.py` passed ON A REAL TPU —
the flags gate kernels whose Mosaic legality interpret mode cannot
prove.  Edits lightgbm_tpu/ops/pallas_segment.py in place and re-runs
the interpret test grid as a sanity check.
"""
import re
import subprocess
import sys

PATH = "lightgbm_tpu/ops/pallas_segment.py"

# the STAGED kernel names come from the shared registry (STAGED_FLAGS in
# pallas_segment.py) so flip/smoke/bench can never disagree on names;
# importing the module would pull jax, so read the literal instead
FLAGS = {"acc": "PARTITION_ACC_VALIDATED",
         "roll": "PARTITION_ACC_ROLL_VALIDATED",
         "repeat": "HIST_REPEAT_VALIDATED"}
_m = re.search(r"STAGED_FLAGS = \{(.*?)\}", open(PATH).read(), re.S)
for k, v in re.findall(r'"(\w+)":\s*"(\w+)"', _m.group(1)):
    FLAGS[k] = v

names = sys.argv[1:]
if not names or any(n not in FLAGS for n in names):
    sys.exit("usage: flip_validated.py {%s}..." % "|".join(sorted(FLAGS)))
src = open(PATH).read()
for n in names:
    flag = FLAGS[n]
    new, cnt = re.subn(r"^%s = False$" % flag, "%s = True" % flag,
                       src, flags=re.M)
    if cnt != 1:
        sys.exit("could not flip %s (already True?)" % flag)
    src = new
    print("flipped", flag)
orig = open(PATH).read()
open(PATH, "w").write(src)
rc = subprocess.run([sys.executable, "-m", "pytest",
                     "tests/test_pallas_segment.py", "-q",
                     "--deselect",
                     "tests/test_pallas_segment.py::test_validated_flags_gate_product_paths",
                     "--deselect",
                     "tests/test_pallas_segment.py::test_partition_hist_flag_staged_off",
                     "--deselect",
                     "tests/test_pallas_segment.py::test_colblock_flag_staged_off",
                     "--deselect",
                     "tests/test_pallas_segment.py::test_ring4_flag_staged_off"]).returncode
if rc != 0:
    open(PATH, "w").write(orig)   # never leave flipped flags with a red grid
    print("interpret grid FAILED — flags reverted")
sys.exit(rc)
