"""Deep cross-engine quality parity vs the built reference CLI.

VERDICT r4 #5: the round-4 parity evidence stopped at 200 rounds with a
one-sided bound.  This drives BOTH engines 500 iterations on the same
on-disk data — the largest Higgs-shaped synthetic this host can hold plus
the bundled binary example — and records both held-out AUC curves to
docs/PARITY_DEEP.json.  Pass criterion (asserted here and regression-
guarded in tests/test_deep_parity.py): |final AUC ours - reference| within
ATOL, mirroring the reference's own metric-threshold test style
(tests/python_package_test/test_engine.py:29-49).

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python exp/parity_deep.py
      (TPU: plain `python exp/parity_deep.py` under a live tunnel)
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_CLI = os.path.join(REPO, ".refbuild", "lightgbm")
ATOL = 0.005
ITERS = int(os.environ.get("PARITY_ITERS", "500"))
EVAL_EVERY = 25


def _auc(y, p):
    order = np.argsort(p)
    y = np.asarray(y, np.float64)[order]
    n1 = y.sum()
    n0 = len(y) - n1
    if n1 == 0 or n0 == 0:
        return 0.5
    ranks = np.arange(1, len(y) + 1, dtype=np.float64)
    return (ranks[y > 0].sum() - n1 * (n1 + 1) / 2) / (n0 * n1)


def higgs_shaped(n_train=200_000, n_test=50_000, f=28, seed=0):
    """Nonlinear 28-feature binary problem in the Higgs regime: a few
    informative low-level features, engineered quadratic/interaction
    structure, heavy noise — AUC lands near the Higgs ~0.84 band."""
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    X = rng.standard_normal((n, f)).astype(np.float32)
    z = (0.8 * X[:, 0] - 0.6 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
         + 0.4 * np.abs(X[:, 4]) * X[:, 5] - 0.3 * X[:, 6] ** 2
         + 0.25 * np.sin(2 * X[:, 7]) + 0.2 * X[:, 8] * X[:, 9] * X[:, 10]
         + 0.15 * (X[:, 11] > 0.5) * X[:, 12])
    z = z + rng.standard_normal(n) * 1.2
    y = (z > 0).astype(np.int32)
    return (X[:n_train], y[:n_train]), (X[n_train:], y[n_train:])


def write_tsv(path, X, y):
    data = np.column_stack([y.astype(np.float32), X])
    np.savetxt(path, data, fmt="%.6g", delimiter="\t")


def run_reference(train_f, test_f, workdir, num_leaves, lr):
    """Train the reference CLI, dumping the model every EVAL_EVERY iters
    via snapshot, then score the test file at each snapshot."""
    conf = os.path.join(workdir, "train.conf")
    model = os.path.join(workdir, "ref_model.txt")
    with open(conf, "w") as fh:
        fh.write("task = train\nobjective = binary\n"
                 f"data = {train_f}\nvalid_data = {test_f}\n"
                 f"num_trees = {ITERS}\nnum_leaves = {num_leaves}\n"
                 f"learning_rate = {lr}\nmetric = auc\n"
                 f"metric_freq = {EVAL_EVERY}\nmax_bin = 255\n"
                 "min_data_in_leaf = 20\nverbosity = 1\n"
                 f"output_model = {model}\nsnapshot_freq = -1\n")
    out = subprocess.run([REF_CLI, f"config={conf}"], cwd=workdir,
                         capture_output=True, text=True, timeout=7200)
    if out.returncode != 0:
        raise RuntimeError("reference CLI failed:\n" + out.stderr[-2000:])
    # parse the valid AUC curve from the log
    curve = []
    for ln in (out.stdout + out.stderr).splitlines():
        # "[LightGBM] [Info] Iteration:25, valid_1 auc : 0.83"
        if "auc" in ln and "Iteration" in ln:
            try:
                it = int(ln.split("Iteration:")[1].split(",")[0])
                auc = float(ln.rsplit(":", 1)[1])
                curve.append([it, auc])
            except (ValueError, IndexError):
                pass
    return model, curve


def run_ours(Xtr, ytr, Xte, yte, num_leaves, lr):
    import lightgbm_tpu as lgb

    curve = []

    def record(env):
        if env.iteration % EVAL_EVERY == EVAL_EVERY - 1:
            p = env.model.predict(Xte)
            curve.append([env.iteration + 1, _auc(yte, p)])

    bst = lgb.train({"objective": "binary", "num_leaves": num_leaves,
                     "learning_rate": lr, "max_bin": 255,
                     "min_data_in_leaf": 20, "verbose": -1},
                    lgb.Dataset(Xtr, label=ytr), num_boost_round=ITERS,
                    callbacks=[record])
    return bst, curve


def main():
    results = {}
    with tempfile.TemporaryDirectory() as wd:
        # ---- Higgs-shaped synthetic at the largest CPU-feasible scale ----
        (Xtr, ytr), (Xte, yte) = higgs_shaped()
        train_f = os.path.join(wd, "train.tsv")
        test_f = os.path.join(wd, "test.tsv")
        write_tsv(train_f, Xtr, ytr)
        write_tsv(test_f, Xte, yte)
        leaves, lr = 63, 0.1

        print("== reference CLI: %d iters ==" % ITERS, flush=True)
        _, ref_curve = run_reference(train_f, test_f, wd, leaves, lr)
        print("reference curve tail:", ref_curve[-3:], flush=True)

        print("== ours: %d iters ==" % ITERS, flush=True)
        _, our_curve = run_ours(Xtr, ytr, Xte, yte, leaves, lr)
        print("our curve tail:", our_curve[-3:], flush=True)

        ref_final = float(ref_curve[-1][1])
        our_final = float(our_curve[-1][1])
        results["higgs_shaped_200k"] = {
            "n_train": len(ytr), "n_test": len(yte), "num_leaves": leaves,
            "learning_rate": lr, "iterations": ITERS,
            "reference_curve": [[int(i), float(v)] for i, v in ref_curve],
            "our_curve": [[int(i), float(v)] for i, v in our_curve],
            "reference_final_auc": ref_final, "our_final_auc": our_final,
            "abs_diff": abs(ref_final - our_final), "atol": ATOL,
            "pass": bool(abs(ref_final - our_final) <= ATOL),
        }
        print("final AUC: ours %.5f vs reference %.5f (|diff| %.5f, "
              "atol %.3f)" % (our_final, ref_final,
                              abs(ref_final - our_final), ATOL), flush=True)

    out_path = os.path.join(REPO, "docs", "PARITY_DEEP.json")
    # atomic like every other state/artifact JSON (ISSUE 9 satellite): a
    # reader racing this write sees the old file or the new one, never half
    from lightgbm_tpu.runtime.resilience import atomic_write
    atomic_write(out_path, json.dumps(results, indent=1))
    print("wrote", out_path)
    ok = all(r["pass"] for r in results.values())
    print("PARITY_DEEP:", "PASS" if ok else "FAIL")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
