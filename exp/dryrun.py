#!/usr/bin/env python
"""Watchdogged multichip dryrun wrapper -> MULTICHIP-style artifact JSON.

The driver's own MULTICHIP artifact records only {rc, tail}; five rounds
of red artifacts (rc=124, hung after "import jax") proved that is not
enough.  This wrapper runs the SAME check — `dryrun_multichip(n)` over a
virtual n-device CPU mesh — but leaves a diagnosable artifact whatever
happens:

* the requested platform is health-probed first in short-deadline
  subprocesses with jittered-backoff retry; a dead/hung platform is
  recorded as a machine-readable `degradation_event` (the dryrun itself
  always runs on the hermetic CPU mesh, so a dead tunnel costs seconds,
  not the driver's whole budget);
* every dryrun stage runs under the resilience watchdog with wall-clock
  timestamps, and the rolling stage trail is embedded in the artifact;
* on a timeout, the artifact carries the faulthandler tracebacks of all
  threads and NAMES the culprit stage — no bare rc=124 is reachable from
  any injected fault (`LGBM_TPU_FAULT=bogus_platform,hang_import:300` is
  the tier-1 pin, tests/test_resilience.py).

Usage:  python exp/dryrun.py [n_devices] [artifact.json]
Env:    LGBM_TPU_DRYRUN_BUDGET (s, default 240)
        LGBM_TPU_PROBE_DEADLINE (s, default 15), LGBM_TPU_PROBE_ATTEMPTS
"""
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.runtime import resilience  # noqa: E402


def main(argv):
    n_devices = int(argv[1]) if len(argv) > 1 else int(
        os.environ.get("NDEV", "8"))
    artifact = argv[2] if len(argv) > 2 else os.path.join(
        REPO, "MULTICHIP_local.json")
    budget = float(os.environ.get("LGBM_TPU_DRYRUN_BUDGET", "240"))
    probe_deadline = float(os.environ.get("LGBM_TPU_PROBE_DEADLINE", "15"))
    probe_attempts = int(os.environ.get("LGBM_TPU_PROBE_ATTEMPTS", "2"))
    t0 = time.monotonic()
    rec = {"n_devices": n_devices, "ok": False, "skipped": False,
           "rc": None, "wrapper": "exp/dryrun.py", "budget_s": budget,
           "t_start": resilience.wallclock()}

    # -- 1. platform health probe + degradation chain -----------------------
    # The dryrun proper always runs on the hermetic virtual-CPU mesh; the
    # probe records whether the ENVIRONMENT's requested platform (the one
    # the driver would bind) is actually alive, and degrades the record to
    # cpu instead of letting a dead tunnel eat the whole budget.
    backend, degradation, probes = resilience.resolve_backend(
        requested=None, deadline=probe_deadline, attempts=probe_attempts,
        n_devices=n_devices)
    rec["platform"] = backend
    rec["platform_probes"] = [{k: v for k, v in p.items() if k != "tail"}
                              for p in probes]
    rec["degradation_event"] = degradation
    if degradation is not None:
        # the hung probe's self-dumped thread tracebacks are the evidence
        # a post-mortem needs; keep the last probe tail that has one
        for p in reversed(probes):
            if p.get("tail"):
                rec["probe_tracebacks"] = p["tail"]
                break

    # -- 2. the dryrun itself, stage-watchdogged ----------------------------
    report_path = os.path.join(tempfile.gettempdir(),
                               "lgbm_tpu_dryrun_stages_%d.json" % os.getpid())
    metrics_path = os.path.join(tempfile.gettempdir(),
                                "lgbm_tpu_dryrun_metrics_%d.jsonl"
                                % os.getpid())
    env = dict(os.environ)
    env["LGBM_TPU_STAGE_REPORT"] = report_path
    # mesh metrics block (ISSUE 10): the dryrun child flushes its
    # registry here; the artifact embeds the {host}-labeled merge
    env["LGBM_TPU_METRICS_FILE"] = metrics_path
    if degradation is not None:
        # belt-and-braces: never let a child of THIS wrapper bind the
        # platform the probe just watched die
        env["JAX_PLATFORMS"] = "cpu"
    remaining = max(budget - (time.monotonic() - t0), 30.0)
    code = ("import __graft_entry__ as g; g.dryrun_multichip(%d)"
            % n_devices)
    try:
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                           timeout=remaining, capture_output=True, text=True)
        rec["rc"] = r.returncode
        rec["ok"] = r.returncode == 0
        rec["tail"] = ((r.stdout or "") + (r.stderr or ""))[-4000:]
    except subprocess.TimeoutExpired as e:
        rec["rc"] = 124
        rec["tail"] = (_txt(e.stdout) + _txt(e.stderr))[-4000:]
        rec["note"] = ("wrapper budget exceeded — the stage trail below "
                       "names the culprit")

    # the rolling stage report survives any way the subprocess died;
    # the tolerant reader degrades a torn/missing file to "no trail"
    try:
        stage_rep = resilience.read_stage_report(report_path)
        if stage_rep is not None:
            rec["stages"] = stage_rep.get("stages", [])
            rec["culprit_stage"] = stage_rep.get("culprit")
            if stage_rep.get("tracebacks"):
                rec["tracebacks"] = stage_rep["tracebacks"]
        else:
            rec["stages"] = []
            rec["culprit_stage"] = None
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass

    # per-host metrics block: the child's last registry snapshot, merged
    # through the same {host}-labeling path a real multi-host gather uses
    try:
        from lightgbm_tpu.runtime import telemetry
        with open(metrics_path) as fh:
            lines = [ln for ln in fh.read().splitlines() if ln.strip()]
        if lines:
            snap = json.loads(lines[-1])
            hosts = ({"0": snap} if "metrics" in snap
                     and "hosts" not in snap else None)
            rec["host_metrics"] = (telemetry.merge_host_snapshots(hosts)
                                   if hosts is not None else snap)
    except (OSError, ValueError):
        pass
    finally:
        try:
            os.unlink(metrics_path)
        except OSError:
            pass

    if not rec["ok"]:
        # a red artifact ships home WITH its evidence: the doctor bundle
        # (probe already recorded above, so probe=False) lands next to
        # the artifact and its manifest rides inside the artifact
        try:
            from lightgbm_tpu.runtime.doctor import collect_debug_bundle
            bundle = collect_debug_bundle(
                out_dir=os.path.dirname(os.path.abspath(artifact)) or ".",
                tag="dryrun", probe=False,
                stage_reports=[report_path], artifact_dir=REPO,
                note="attached by exp/dryrun.py on rc=%s" % rec["rc"])
            rec["debug_bundle"] = {"path": bundle["path"],
                                   "manifest": bundle["manifest"]}
        except Exception as e:   # noqa: BLE001 — artifact must still land
            rec["debug_bundle"] = {"error": "%s: %s"
                                   % (type(e).__name__, e)}

    rec["elapsed_s"] = round(time.monotonic() - t0, 1)
    rec["within_budget"] = rec["elapsed_s"] <= budget
    resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
    print("dryrun wrapper: ok=%s rc=%s elapsed=%.1fs degradation=%s "
          "artifact=%s" % (rec["ok"], rec["rc"], rec["elapsed_s"],
                           "yes" if degradation else "no", artifact),
          flush=True)
    return 0 if rec["ok"] else 1


def _txt(v):
    if v is None:
        return ""
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else v


if __name__ == "__main__":
    sys.exit(main(sys.argv))
