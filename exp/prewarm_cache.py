#!/usr/bin/env python
"""Prewarm the dryrun's persistent compile cache + commit a stage log.

The driver's `dryrun_multichip` artifact has been red for five rounds for
a feature that is green by hand (VERDICT r5 Weak #1b/#1c) — cold XLA
compiles under an unattended budget, with the death point invisible
afterwards.  Two fixes compose here:

1. **warm**: run the hermetic dryrun once NOW, which populates the
   host-fingerprinted persistent compilation cache
   (`__graft_entry__._hermetic_cpu_env` sets
   ``JAX_COMPILATION_CACHE_DIR=~/.cache/jax_dryrun_<fingerprint>``); the
   driver's next invocation on this host compiles nothing and runs in
   seconds;
2. **visible**: persist the run's per-stage wall-clock trail to a log
   that is COMMITTED to the repo (exp/logs/DRYRUN_STAGES.json), so even
   when a later unattended run dies, the last known-good stage timings —
   and the point past which no stage ever reported — are readable from
   the repo alone.

Usage:  python exp/prewarm_cache.py [n_devices] [log_path]
Env:    everything exp/dryrun.py honors (LGBM_TPU_DRYRUN_BUDGET, ...).
"""
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.runtime import resilience, warmup  # noqa: E402

#: default base of the PRODUCT compile cache (runtime/warmup.py seam;
#: $LGBM_TPU_COMPILE_CACHE overrides) — the dryrun subprocess keeps its
#: own self-contained cache dir from __graft_entry__._hermetic_cpu_env
#: because the bootstrap runs before this package is importable.
DEFAULT_CACHE_BASE = "~/.cache/lgbm_tpu_compile_cache"


def main(argv):
    n_devices = int(argv[1]) if len(argv) > 1 else int(
        os.environ.get("NDEV", "8"))
    log_path = argv[2] if len(argv) > 2 else os.path.join(
        REPO, "exp", "logs", "DRYRUN_STAGES.json")
    os.makedirs(os.path.dirname(log_path), exist_ok=True)

    artifact = os.path.join(tempfile.gettempdir(),
                            "lgbm_tpu_prewarm_%d.json" % os.getpid())
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "exp", "dryrun.py"),
         str(n_devices), artifact], cwd=REPO, capture_output=True,
        text=True)
    warm_s = round(time.monotonic() - t0, 1)
    try:
        rec = json.load(open(artifact))
    except (OSError, ValueError):
        rec = {"ok": False, "note": "dryrun wrapper left no artifact",
               "tail": (r.stdout + r.stderr)[-2000:]}
    finally:
        try:
            os.unlink(artifact)
        except OSError:
            pass

    # also arm + report the PRODUCT warm-start cache through the ISSUE 15
    # seam, so the committed log names the fingerprinted subdir every
    # warm task=... run on this host will hit (de-duplicated: the seam
    # owns the fingerprint; only the pre-import dryrun bootstrap keeps
    # its own dir)
    try:
        warmup.enable_compile_cache(
            os.environ.get(warmup.CACHE_ENV, DEFAULT_CACHE_BASE))
        warmup_cache = warmup.cache_status()
    except Exception as e:    # noqa: BLE001 — log stays committable
        warmup_cache = {"error": "%s: %s" % (type(e).__name__, e)}

    log = {
        "purpose": "prewarm the dryrun's persistent XLA compile cache and "
                   "record the stage trail; the driver's unattended "
                   "dryrun_multichip runs WARM after this and any later "
                   "death point is diffable against these stage timings",
        "prewarmed_at": resilience.wallclock(),
        "host_cache_dir": os.path.expanduser("~/.cache"),
        "warmup_cache": warmup_cache,
        "n_devices": n_devices,
        "prewarm_run_ok": rec.get("ok"),
        "prewarm_run_rc": rec.get("rc"),
        "prewarm_elapsed_s": warm_s,
        "platform": rec.get("platform"),
        "degradation_event": rec.get("degradation_event"),
        "stages": rec.get("stages", []),
        "culprit_stage": rec.get("culprit_stage"),
    }
    if rec.get("tracebacks"):
        log["tracebacks"] = rec["tracebacks"]
    resilience.atomic_write(log_path, json.dumps(log, indent=1) + "\n")
    print("prewarm: ok=%s elapsed=%.1fs stages=%d log=%s"
          % (log["prewarm_run_ok"], warm_s, len(log["stages"]), log_path),
          flush=True)
    return 0 if log["prewarm_run_ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
