"""Validate the Pallas building blocks for the segment-histogram kernel:
scalar SMEM operands, dynamic fori_loop trip count, manual HBM->VMEM DMA at
dynamic offsets, joint one-hot dot with f32 accumulation."""
import functools
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

F = 28
B = 256
C = 512          # rows per chunk
FB = F * B

N = 2 ** 21
rng = np.random.default_rng(0)
bins_np = rng.integers(0, B, size=(N, F), dtype=np.int32)
P = F + 4  # bins + grad, hess, mask, pad
payload_np = np.zeros((N, P), np.float32)
payload_np[:, :F] = bins_np
payload_np[:, F + 0] = rng.standard_normal(N)
payload_np[:, F + 1] = rng.random(N)
payload_np[:, F + 2] = 1.0
payload = jnp.asarray(payload_np)


def _kernel(scalars_ref, payload_hbm, out_ref, chunk_vmem, sem):
    start = scalars_ref[0]
    nchunks = scalars_ref[1]

    @pl.when(True)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    def body(k, _):
        dma = pltpu.make_async_copy(
            payload_hbm.at[pl.ds(start + k * C, C), :],
            chunk_vmem, sem)
        dma.start()
        dma.wait()
        chunk = chunk_vmem[:]
        binsf = chunk[:, :F].astype(jnp.int32)          # [C, F]
        jidx = binsf + lax.broadcasted_iota(jnp.int32, (C, F), 1) * B
        iota_fb = lax.broadcasted_iota(jnp.int32, (C, FB), 1)
        onehot = (jidx[:, :, None] ==
                  iota_fb.reshape(C, F, B)).astype(jnp.bfloat16).reshape(C, FB)
        vals = jnp.concatenate(
            [chunk[:, F:F + 3], jnp.zeros((C, 5), jnp.float32)], axis=1)
        acc = lax.dot_general(
            onehot, vals.astype(jnp.bfloat16),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [FB, 8]
        out_ref[:] += acc
        return 0

    lax.fori_loop(0, nchunks, body, 0)


@functools.partial(jax.jit, static_argnames=())
def segment_hist(payload, start, nchunks):
    scalars = jnp.stack([start, nchunks]).astype(jnp.int32)
    return pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((C, P), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((FB, 8), jnp.float32),
    )(scalars, payload)


def ref_hist(payload, start, count):
    seg = np.asarray(payload)[start:start + count]
    hist = np.zeros((F, B, 3), np.float32)
    for f in range(F):
        for d in range(3):
            np.add.at(hist[f, :, d], seg[:, f].astype(np.int64),
                      seg[:, F + d])
    return hist


if __name__ == "__main__":
    print("backend:", jax.default_backend())
    # correctness on a small segment
    start, count = 1024, 4 * C
    h = segment_hist(payload, jnp.int32(start), jnp.int32(count // C))
    h = np.asarray(h)[:, :3].reshape(F, B, 3)
    hr = ref_hist(payload, start, count)
    err = np.abs(h - hr).max()
    print("max abs err (bf16 vals):", err, "rel:",
          err / (np.abs(hr).max() + 1e-9))

    # timing: full-N pass
    nch = jnp.int32(N // C)
    out = segment_hist(payload, jnp.int32(0), nch)
    jax.block_until_ready(out)
    for r in range(3):
        t0 = time.perf_counter()
        out = segment_hist(payload, jnp.int32(0), nch)
        jax.block_until_ready(out)
        print("full-N pass: %.2f ms" % ((time.perf_counter() - t0) * 1e3))
    # timing: small segment (64 chunks = 32k rows)
    for r in range(3):
        t0 = time.perf_counter()
        out = segment_hist(payload, jnp.int32(12345 // C * C), jnp.int32(64))
        jax.block_until_ready(out)
        print("64-chunk segment: %.3f ms" % ((time.perf_counter() - t0) * 1e3))
