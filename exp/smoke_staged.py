"""Unattended staged-kernel validation for a hardware window nobody is
watching (the driver's end-of-round bench).

Round-4 discipline keeps new Mosaic kernels OFF until a hardware smoke
proves them — but every validation so far needed a live operator, and
the tunnel has been dead for the whole of round 5.  This script is the
operator-less version: it validates each staged kernel ON-CHIP against
the hardware-validated kernels (exactness) and fetch-forced races
(performance), then prints ONE json line of per-flag verdicts.  bench.py
runs it in a killable subprocess and enables, IN-PROCESS ONLY, exactly
the flags that passed — so a Mosaic crash costs the verdict, never the
bench, and the tree's defaults stay untouched for a human to flip with
the recorded evidence (exp/flip_validated.py).

Exit code is always 0; the verdicts carry the information.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

verdicts = {"merged": False, "colblock": False, "ring4": False,
            "blocks": False, "frontier": False, "quant": False}
notes = {}


def emit():
    print(json.dumps({"verdicts": verdicts, "notes": notes}), flush=True)


def median_ms(fn, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[reps // 2] * 1e3


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() != "tpu":
        notes["platform"] = jax.default_backend()
        emit()
        return

    from lightgbm_tpu.ops import segment as seg
    from lightgbm_tpu.ops import pallas_segment as pseg

    rng = np.random.default_rng(0)
    N, F, B, P = 8192, 28, 256, 128
    g, h, c, VAL = F, F + 1, F + 2, F + 3
    pay = np.zeros((N + seg.GUARD, P), np.float32)
    pay[:N, :F] = rng.integers(0, B, (N, F))
    pay[:N, g] = rng.standard_normal(N)
    pay[:N, h] = rng.random(N) + 0.1
    pay[:N, c] = 1.0
    pay = jnp.asarray(pay)
    pred = seg.SplitPredicate(
        col=jnp.int32(2), threshold=jnp.int32(100),
        default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
        missing_type=jnp.int32(0), num_bin=jnp.int32(B),
        default_bin=jnp.int32(0), offset=jnp.int32(0),
        identity=jnp.bool_(True), bitset=jnp.zeros(B, jnp.int32))
    kw = dict(num_features=F, grad_col=g, hess_col=h, cnt_col=c)

    # ---- merged partition+hist: exact vs (validated acc partition +
    # validated hist kernel), then race the per-split device work ----
    try:
        for (s_, c_) in ((128, 3000), (7, 8000)):
            pm, _, nlm, hl, hr = pseg.partition_segment_hist(
                pay, jnp.zeros_like(pay), jnp.int32(s_), jnp.int32(c_),
                pred, jnp.float32(1.5), jnp.float32(-2.5), VAL, B, **kw)
            pr, _, nlr = pseg.partition_segment_acc(
                pay, jnp.zeros_like(pay), jnp.int32(s_), jnp.int32(c_),
                pred, jnp.float32(1.5), jnp.float32(-2.5), VAL, B)
            assert int(nlm) == int(nlr)
            assert float(jnp.abs(pm - pr).max()) == 0.0
            hlr = pseg.segment_histogram(pr, jnp.int32(s_), nlr,
                                         num_bins=B, **kw)
            hrr = pseg.segment_histogram(pr, jnp.int32(s_) + nlr,
                                         jnp.int32(c_) - nlr,
                                         num_bins=B, **kw)
            herr = max(float(jnp.abs(hl - hlr).max()),
                       float(jnp.abs(hr - hrr).max()))
            assert herr < 1e-3, herr

        def split_mode():
            h_ = pseg.segment_histogram(pay, jnp.int32(0), jnp.int32(N // 2),
                                        num_bins=B, **kw)
            out = pseg.partition_segment_acc(
                pay, jnp.zeros_like(pay), jnp.int32(0), jnp.int32(N), pred,
                jnp.float32(1.), jnp.float32(-1.), VAL, B)
            np.asarray(h_)[0, 0, 2]          # fetch-force
            np.asarray(out[0])[0, 0]

        def merged_mode():
            out = pseg.partition_segment_hist(
                pay, jnp.zeros_like(pay), jnp.int32(0), jnp.int32(N), pred,
                jnp.float32(1.), jnp.float32(-1.), VAL, B, **kw)
            np.asarray(out[0])[0, 0]

        split_mode(); merged_mode()          # compile outside the race
        ms_split = median_ms(split_mode)
        ms_merged = median_ms(merged_mode)
        notes["merged_ms"] = {"split": round(ms_split, 2),
                              "merged": round(ms_merged, 2)}
        verdicts["merged"] = ms_merged <= ms_split * 1.05
    except Exception as e:
        notes["merged"] = "%s: %s" % (type(e).__name__, str(e)[:300])

    # ---- frontier-batched histogram: K segments, one grid-(K,) dispatch.
    # Exact vs the validated single-segment kernel per slice, then race K
    # sequential dispatches vs one batched dispatch (the lever is the
    # per-dispatch sequencing cost frontier batching amortizes).  Also
    # answers the pltpu.repeat semantics question on this jax: this
    # kernel shares the expand machinery, so a layout flip fails the
    # exactness leg loudly instead of silently on the bench. ----
    try:
        starts = jnp.asarray([0, 2048, 4096, 7, 6144, 0], jnp.int32)
        counts = jnp.asarray([2000, 2048, 1000, 2041, 2000, 0], jnp.int32)
        hb = pseg.segment_histogram_batched(pay, starts, counts,
                                            num_bins=B, **kw)
        for k in range(6):
            h1 = pseg.segment_histogram(pay, starts[k], counts[k],
                                        num_bins=B, **kw)
            assert float(jnp.abs(hb[k] - h1).max()) == 0.0, k

        def seq_mode():
            for k in range(6):
                np.asarray(pseg.segment_histogram(
                    pay, starts[k], counts[k], num_bins=B, **kw))[0, 0, 2]

        def batched_mode():
            np.asarray(pseg.segment_histogram_batched(
                pay, starts, counts, num_bins=B, **kw))[0, 0, 0, 2]

        seq_mode(); batched_mode()
        ms_seq = median_ms(seq_mode)
        ms_bat = median_ms(batched_mode)
        notes["frontier_ms"] = {"sequential6": round(ms_seq, 2),
                                "batched6": round(ms_bat, 2)}
        verdicts["frontier"] = ms_bat <= ms_seq * 1.05
    except Exception as e:
        notes["frontier"] = "%s: %s" % (type(e).__name__, str(e)[:300])

    # ---- quantized histogram: the int8 x one-hot -> int32 MXU kernel
    # (gradient_quantization, HIST_QUANT_VALIDATED).  Exactness leg is
    # BIT equality against the portable integer engine (integer
    # accumulation is order-free, so zero tolerance); the race is against
    # the validated f32 kernel over the same rows — the lever is dropping
    # the 7 bf16 part-rows to 3 int8 value rows plus the s8 contraction.
    # The one unproven Mosaic pattern is the s8xs8->s32 dot_general. ----
    try:
        payq = np.array(pay)
        payq[:N, g] = rng.integers(-127, 128, N)
        payq[:N, h] = rng.integers(0, 128, N)
        payq = jnp.asarray(payq)
        for (s_, c_) in ((0, 8000), (7, 4097), (2048, 1), (0, 0)):
            hq = pseg.segment_histogram_quant(payq, jnp.int32(s_),
                                              jnp.int32(c_), num_bins=B,
                                              **kw)
            hr = seg.segment_histogram(payq, jnp.int32(s_), jnp.int32(c_),
                                       num_bins=B, quantized=True, **kw)
            assert int(jnp.abs(hq - hr).max()) == 0, (s_, c_)

        def quant_fn():
            np.asarray(pseg.segment_histogram_quant(
                payq, jnp.int32(0), jnp.int32(N), num_bins=B,
                **kw))[0, 0, 2]

        def f32_fn():
            np.asarray(pseg.segment_histogram(
                payq, jnp.int32(0), jnp.int32(N), num_bins=B,
                **kw))[0, 0, 2]

        quant_fn(); f32_fn()
        ms_q = median_ms(quant_fn)
        ms_f = median_ms(f32_fn)
        notes["quant_ms"] = {"quant_int8": round(ms_q, 2),
                             "f32_kernel": round(ms_f, 2)}
        verdicts["quant"] = ms_q <= ms_f * 1.05
    except Exception as e:
        notes["quant"] = "%s: %s" % (type(e).__name__, str(e)[:300])

    # ---- colblock ultra-wide hist: exact vs portable, race vs portable
    # (its activation shapes otherwise run the portable lax path) ----
    try:
        Fw, Bw = 1500, 64
        Pw = -(-(Fw + 8) // 128) * 128
        payw = np.zeros((N + seg.GUARD, Pw), np.float32)
        payw[:N, :Fw] = rng.integers(0, Bw, (N, Fw))
        payw[:N, Fw] = rng.standard_normal(N)
        payw[:N, Fw + 1] = rng.random(N) + 0.1
        payw[:N, Fw + 2] = 1.0
        payw = jnp.asarray(payw)
        kww = dict(num_features=Fw, num_bins=Bw, grad_col=Fw,
                   hess_col=Fw + 1, cnt_col=Fw + 2)
        for (s_, c_) in ((0, 8000), (7, 4097)):
            hcb = pseg.segment_histogram_colblock(
                payw, jnp.int32(s_), jnp.int32(c_), **kww)
            href = seg.segment_histogram(payw, jnp.int32(s_),
                                         jnp.int32(c_), **kww)
            assert float(jnp.abs(hcb - href).max()) < 1e-3

        def cb():
            np.asarray(pseg.segment_histogram_colblock(
                payw, jnp.int32(0), jnp.int32(N), **kww))[0, 0, 2]

        def portable():
            np.asarray(seg.segment_histogram(
                payw, jnp.int32(0), jnp.int32(N), **kww))[0, 0, 2]

        cb(); portable()
        ms_cb = median_ms(cb)
        ms_port = median_ms(portable)
        notes["colblock_ms"] = {"colblock": round(ms_cb, 2),
                                "portable": round(ms_port, 2)}
        verdicts["colblock"] = ms_cb <= ms_port * 1.05
    except Exception as e:
        notes["colblock"] = "%s: %s" % (type(e).__name__, str(e)[:300])

    # ---- column-block PARTITION (ultra-wide): exact vs portable, race
    # vs portable (its activation shapes have no other kernel path) ----
    try:
        PBF, PBB = 1200, 64
        PBP = -(-(PBF + 8) // 128) * 128
        paypb = np.zeros((N + seg.GUARD, PBP), np.float32)
        paypb[:N, :PBF] = rng.integers(0, PBB, (N, PBF))
        paypb[:N, PBF] = rng.standard_normal(N)
        paypb[:N, PBF + 1] = rng.random(N) + 0.1
        paypb[:N, PBF + 2] = 1.0
        paypb = jnp.asarray(paypb)
        PBVAL = PBF + 3
        predpb = seg.SplitPredicate(
            col=jnp.int32(700), threshold=jnp.int32(30),
            default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
            missing_type=jnp.int32(0), num_bin=jnp.int32(PBB),
            default_bin=jnp.int32(0), offset=jnp.int32(0),
            identity=jnp.bool_(True), bitset=jnp.zeros(PBB, jnp.int32))
        for (s_, c_) in ((128, 3000), (7, 8000)):
            pb, _, nlb = pseg.partition_segment_acc_blocks(
                paypb, jnp.zeros_like(paypb), jnp.int32(s_), jnp.int32(c_),
                predpb, jnp.float32(1.5), jnp.float32(-2.5), PBVAL, PBB)
            pr, _, nlr = seg.partition_segment(
                paypb, jnp.zeros_like(paypb), jnp.int32(s_), jnp.int32(c_),
                predpb, jnp.float32(1.5), jnp.float32(-2.5), PBVAL)
            assert int(nlb) == int(nlr)
            assert float(jnp.abs(pb - pr).max()) == 0.0

        def blocks_fn():
            out = pseg.partition_segment_acc_blocks(
                paypb, jnp.zeros_like(paypb), jnp.int32(0), jnp.int32(N),
                predpb, jnp.float32(1.), jnp.float32(-1.), PBVAL, PBB)
            np.asarray(out[0])[0, 0]

        def portable_fn():
            out = seg.partition_segment(
                paypb, jnp.zeros_like(paypb), jnp.int32(0), jnp.int32(N),
                predpb, jnp.float32(1.), jnp.float32(-1.), PBVAL)
            np.asarray(out[0])[0, 0]

        blocks_fn(); portable_fn()
        ms_b = median_ms(blocks_fn)
        ms_p = median_ms(portable_fn)
        notes["blocks_ms"] = {"blocks": round(ms_b, 2),
                              "portable": round(ms_p, 2)}
        verdicts["blocks"] = ms_b <= ms_p * 1.05
    except Exception as e:
        notes["blocks"] = "%s: %s" % (type(e).__name__, str(e)[:300])

    # ---- 4-deep ring: exact vs depth 2, race both depths (acc AND
    # merged variants must both be legal before the shared flag flips) ----
    try:
        for depth_fn in (
            lambda rd: pseg.partition_segment_acc(
                pay, jnp.zeros_like(pay), jnp.int32(128), jnp.int32(7000),
                pred, jnp.float32(1.5), jnp.float32(-2.5), VAL, B,
                ring_depth=rd),
            lambda rd: pseg.partition_segment_hist(
                pay, jnp.zeros_like(pay), jnp.int32(128), jnp.int32(7000),
                pred, jnp.float32(1.5), jnp.float32(-2.5), VAL, B,
                ring_depth=rd, **kw),
        ):
            o2 = depth_fn(2)
            o4 = depth_fn(4)
            assert int(o2[2]) == int(o4[2])
            assert float(jnp.abs(o4[0] - o2[0]).max()) == 0.0

        def acc_at(rd):
            def fn():
                out = pseg.partition_segment_acc(
                    pay, jnp.zeros_like(pay), jnp.int32(0), jnp.int32(N),
                    pred, jnp.float32(1.), jnp.float32(-1.), VAL, B,
                    ring_depth=rd)
                np.asarray(out[0])[0, 0]
            return fn

        acc_at(2)(); acc_at(4)()
        ms2 = median_ms(acc_at(2))
        ms4 = median_ms(acc_at(4))
        notes["ring_ms"] = {"ring2": round(ms2, 2), "ring4": round(ms4, 2)}
        ring4_ok = ms4 <= ms2 * 1.05
        if verdicts["merged"]:
            # the shared flag also switches the MERGED kernel's ring, and
            # if the merged verdict passed the bench will run THAT variant
            # hot — its depth-4 performance must be measured too, not
            # inferred from the acc race
            def merged_at(rd):
                def fn():
                    out = pseg.partition_segment_hist(
                        pay, jnp.zeros_like(pay), jnp.int32(0),
                        jnp.int32(N), pred, jnp.float32(1.),
                        jnp.float32(-1.), VAL, B, ring_depth=rd, **kw)
                    np.asarray(out[0])[0, 0]
                return fn

            merged_at(4)()
            mm2 = median_ms(merged_at(2))
            mm4 = median_ms(merged_at(4))
            notes["ring_ms"]["merged_ring2"] = round(mm2, 2)
            notes["ring_ms"]["merged_ring4"] = round(mm4, 2)
            ring4_ok = ring4_ok and mm4 <= mm2 * 1.05
        verdicts["ring4"] = ring4_ok
    except Exception as e:
        notes["ring4"] = "%s: %s" % (type(e).__name__, str(e)[:300])

    emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never die silently: the verdict line IS the API
        notes["fatal"] = "%s: %s" % (type(e).__name__, str(e)[:300])
        emit()
