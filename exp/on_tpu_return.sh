#!/usr/bin/env bash
# Run when the axon TPU tunnel comes back: validates everything that could
# not be hardware-tested while it was down, then takes a bench reading.
set -e
cd "$(dirname "$0")/.."
echo "=== 1. kernels exact vs portable (incl. the 2-pass partition) ==="
timeout 400 python exp/smoke_tpu_kernels.py 2>&1 | grep -vE "WARN|INFO|libtpu|common_lib|Failed to find|Logging" | tail -8
echo "=== 2. grower profile (fixed cost + scaling) ==="
timeout 500 python exp/prof_grow_small.py 2>&1 | grep "grow:" || true
echo "=== 3. bench at 2M rows ==="
BENCH_ROWS=2000000 BENCH_TEST_ROWS=200000 BENCH_ITERS=10 timeout 550 python bench.py 2>&1 | grep '"metric"'
