#!/usr/bin/env bash
# Run when the axon TPU tunnel comes back: validates everything that could
# not be hardware-tested while it was down, then takes a bench reading.
set -e -o pipefail
cd "$(dirname "$0")/.."
# ISSUE 15: ONE persistent XLA program cache across the whole window, so
# steps 1-13 stop recompiling each other's programs (every python entry
# point honors this env; the fingerprinted subdir keys backend + jax
# version + staged flags, so the step-1b flag flips get their own cache
# instead of poisoning this one).
export LGBM_TPU_COMPILE_CACHE="${LGBM_TPU_COMPILE_CACHE:-$HOME/.cache/lgbm_tpu_compile_cache}"
echo "compile cache armed: $LGBM_TPU_COMPILE_CACHE"
echo "=== 0. resilience: watchdogged dryrun + platform health (ISSUE 4) ==="
echo "   (exp/dryrun.py probes the real platform with a short deadline,"
echo "    records a degradation_event if the tunnel is dead, and runs the"
echo "    stage-watchdogged multichip dryrun — the artifact JSON carries"
echo "    per-stage wall-clock timestamps and, on any timeout, the"
echo "    faulthandler dump.  docs/RESILIENCE.md has the failure model.)"
timeout 300 python exp/dryrun.py 8 MULTICHIP_local.json \
  && echo "   dryrun artifact: MULTICHIP_local.json" \
  || echo "   dryrun NOT green — read MULTICHIP_local.json (culprit_stage, degradation_event)"
echo "=== 0b. resilience: snapshot/resume under injected preemption ==="
timeout 400 python -m pytest tests/test_resilience.py -q -x \
  -k "sigterm or byte_for_byte" 2>&1 | tail -2 \
  || echo "   resume byte-identity FAILED on this hardware — investigate before trusting snapshots"
echo "=== 1. kernels exact vs portable (incl. the 2-pass partition) ==="
timeout 400 python exp/smoke_tpu_kernels.py 2>&1 | grep -vE "WARN|INFO|libtpu|common_lib|Failed to find|Logging" | tail -8
echo "=== 1b. IF step 1 was green: flip remaining validated kernel flags ==="
echo "   (acc/roll/repeat were validated + flipped in round 4's second"
echo "    window; staged kernels now: MERGED partition+hist, COLBLOCK"
echo "    ultra-wide histogram, BLOCKS partition, RING4, and the"
echo "    FRONTIER batched histogram — inspect the smoke sections, then"
echo "    python exp/flip_validated.py merged colblock frontier ..."
echo "    and re-run this script so steps 2+ measure the flipped kernels)"
echo "   NOTE: this round's CPU jax changed pltpu.repeat's INTERPRET"
echo "   emulation to element-wise repeat (the kernels' one-hot math"
echo "   assumes the hardware-validated tile-concat layout, so the"
echo "   repeat-mode interpret tests fail on CPU).  Step 1 + the smoke's"
echo "   exactness legs decide whether REAL hardware semantics moved too;"
echo "   if they did, HIST_REPEAT_VALIDATED must be reverted to False."
echo "=== 2. grower profile (fixed cost + scaling) ==="
timeout 500 python exp/prof_grow_small.py 2>&1 | grep "grow:" || true
echo "=== 3. bench at 2M rows ==="
BENCH_ROWS=2000000 BENCH_TEST_ROWS=200000 BENCH_ITERS=10 timeout 550 python bench.py 2>&1 | grep '"metric"'
echo "=== 3b. bench at FULL Higgs scale (10.5M x 28) ==="
timeout 3000 python bench.py 2>&1 | grep '"metric"' || echo "full-scale bench failed/oom"
echo "=== 4. mesh fast path on the real chip count (single-chip smoke) ==="
timeout 400 python - <<'PYEOF' 2>&1 | tail -3
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.default_rng(0)
X = rng.standard_normal((200000, 28)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
bst = lgb.train({"objective": "binary", "num_leaves": 255, "verbose": -1},
                lgb.Dataset(X, label=y), num_boost_round=5)
assert bst._engine._fast_active, "fell off the fast path on TPU"
print("single-chip 200k x 28 x 255 leaves: 5 iters ok, fast path active")
PYEOF
echo "=== 4b. shard_map + Pallas kernels compile together (1-device TPU mesh) ==="
timeout 400 python - <<'PYEOF' 2>&1 | tail -3
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.default_rng(0)
X = rng.standard_normal((100000, 28)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
bst = lgb.train({"objective": "binary", "num_leaves": 63, "verbose": -1,
                 "tree_learner": "data"},
                lgb.Dataset(X, label=y), num_boost_round=3)
assert bst._engine._fast_active, "mesh fast path inactive on TPU"
print("tree_learner=data on the real-chip mesh: 3 iters ok (Pallas inside shard_map)")
PYEOF
echo "=== 4c. frontier-batched grower A/B (after flip_validated.py frontier) ==="
echo "    (staged: FRONTIER_BATCH_VALIDATED gates the batched grower on the"
echo "     pallas path; the A/B only measures the lever once it is flipped."
echo "     Compare sec_per_iter and split_rounds_per_tree against step 3.)"
BENCH_FRONTIER_BATCH=8 BENCH_ROWS=2000000 BENCH_TEST_ROWS=200000 BENCH_ITERS=10 \
  timeout 550 python bench.py 2>&1 | grep '"metric"' || echo "frontier A/B failed"
echo "=== 4d. quantized-gradient A/B (gradient_quantization, ISSUE 2) ==="
echo "    (the quantized LAX engine runs regardless of staged flags; the"
echo "     int8 MXU kernel additionally stages behind HIST_QUANT_VALIDATED —"
echo "     inspect the smoke's QUANT section, then flip_validated.py quant"
echo "     and re-run.  Compare sec_per_iter_quant / auc_delta_vs_f32.)"
BENCH_HIST_QUANT=int8 BENCH_ROWS=2000000 BENCH_TEST_ROWS=200000 BENCH_ITERS=10 \
  timeout 900 python bench.py 2>&1 | grep '"metric"' || echo "quant A/B failed"
echo "=== 5. in-loop chunk-size A/B (VERDICT r4 #7 lever) ==="
LIGHTGBM_TPU_CHUNK=512 BENCH_ROWS=2000000 BENCH_TEST_ROWS=200000 BENCH_ITERS=10 \
  timeout 550 python bench.py 2>&1 | grep '"metric"' || echo "chunk=512 A/B failed"
echo "=== 6. feature-parallel fast path on the real chip ==="
timeout 400 python - <<'PYEOF2' 2>&1 | tail -2
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.default_rng(0)
X = rng.standard_normal((100000, 28)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
bst = lgb.train({"objective": "binary", "num_leaves": 63, "verbose": -1,
                 "tree_learner": "feature"},
                lgb.Dataset(X, label=y), num_boost_round=3)
assert bst._engine._fast_active, "feature-parallel fell off the fast path"
print("tree_learner=feature on the real chip: 3 iters ok, fast path active")
PYEOF2
echo "=== 7. serving runtime on the real chip (ISSUE 7) ==="
echo "    (micro-batched device serving + degradation + hot swap;"
echo "     BENCH_SERVE rides the full bench too — this is the quick"
echo "     standalone reading at the serving shape)"
timeout 400 python - <<'PYEOF3' 2>&1 | tail -4
import json, os
os.environ.setdefault("BENCH_SERVE_SECONDS", "8")
import bench
print(json.dumps(bench.bench_serve(), indent=1))
PYEOF3
echo "=== 7a. live /metrics scrape during device serving (ISSUE 9) ==="
echo "    (task=serve with metrics_port=0: drive a few hundred requests,"
echo "     scrape the Prometheus endpoint, and print the serving-latency"
echo "     quantiles the registry derived — the same numbers BENCH_SERVE"
echo "     reports.  docs/OBSERVABILITY.md is the runbook.)"
timeout 300 python - <<'PYEOF4' 2>&1 | tail -8
import json, tempfile, threading, time, urllib.request
import numpy as np
import bench
from lightgbm_tpu.runtime import publish as pubmod
from lightgbm_tpu.runtime.serving import ServingRuntime

with tempfile.TemporaryDirectory(prefix="metrics_scrape_") as d:
    pub = pubmod.ModelPublisher(d + "/pub", keep_last=0)
    pub.publish(bench.synth_serving_model(50, 31).save_model_to_string(),
                meta={"cycle": 1})
    rng = np.random.default_rng(5)
    with ServingRuntime(publish_dir=d + "/pub", metrics_port=0) as rt:
        lat = []
        for _ in range(300):
            t0 = time.perf_counter()
            rt.predict(rng.standard_normal((8, 28)))
            lat.append(time.perf_counter() - t0)
        url = "http://127.0.0.1:%d/metrics" % rt.metrics_port
        text = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "lgbm_serve_latency_seconds_bucket" in text
        q = rt.stats()["latency_quantiles_s"]
        print("scraped %d bytes from %s" % (len(text), url))
        print("registry p50/p99: %.4fs / %.4fs  (client p50 %.4fs over "
              "%d requests)" % (q["p50"], q["p99"],
                                float(np.percentile(lat, 50)), len(lat)))
PYEOF4
echo "=== 7b. chaos-serve soak (device path under fault churn) ==="
timeout 400 python exp/chaos_serve.py 8 /tmp/chaos_serve_tpu.json \
  || echo "chaos-serve soak FAILED on hardware — inspect /tmp/chaos_serve_tpu.json"
echo "=== 8. streaming-ingest bench (ISSUE 8) ==="
echo "    (file parse vs zero-copy dense/CSR push vs binary-cache hit;"
echo "     bins asserted identical across every path — rides the full"
echo "     bench too, this is the standalone full-scale reading)"
BENCH_INGEST_ROWS=1000000 timeout 500 python - <<'PYEOF5' 2>&1 | tail -14
import json
import bench
print(json.dumps(bench.bench_ingest(), indent=1))
PYEOF5
echo "=== 9. device-time attribution + doctor bundle (ISSUE 10) ==="
echo "    (BENCH_ATTRIB on a warm 2M-row booster: compile/dispatch/device/"
echo "     fetch decomposition + the steady-state zero-retrace pin, with"
echo "     per-site cost_analysis FLOPs/bytes.  Read it as: device share"
echo "     low -> dispatch/fetch bound (pipeline + CHUNK levers); high ->"
echo "     kernel bound (staged kernels); any retrace -> fix shape"
echo "     bucketing FIRST.  docs/OBSERVABILITY.md 'Attribution workflow'.)"
BENCH_ROWS=2000000 BENCH_ITERS=8 BENCH_PREDICT=0 BENCH_ONLINE=0 \
  BENCH_SERVE=0 BENCH_INGEST=0 BENCH_TELEMETRY=0 BENCH_HIST_QUANT=0 \
  timeout 900 python bench.py > /tmp/bench_attrib_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/bench_attrib_tpu.json')); print(json.dumps(d.get('attrib'), indent=1))" \
  || echo "   attrib bench FAILED — /tmp/bench_attrib_tpu.json (or stderr above) has the stage trail"
echo "    collate the round trajectory (flags >10% regressions vs best prior):"
timeout 60 python helper/bench_history.py || echo "   REGRESSION flagged — read the table above before shipping this round"
echo "    one-command debug bundle: ships probe + env + trails + metrics +"
echo "    compile ledger + newest artifacts; COMMIT the printed manifest"
echo "    line with the round's artifacts so the window leaves evidence"
timeout 120 python -m lightgbm_tpu task=doctor output_dir=exp/logs 2>&1 | head -3 \
  || echo "   doctor FAILED — collect /tmp manually"
echo "=== 10. production-sim soak on hardware (ISSUE 11) ==="
echo "    (closed loop: continuous trainer + 2 serving replicas sharing"
echo "     one publish dir, diurnal/bursty/step load with priority/quota/"
echo "     policy knobs live, LGBM_TPU_FAULT churn on — the device path"
echo "     now serves real micro-batches, so p99/capacity here are the"
echo "     first HARDWARE serving numbers.  Zero wrong-generation and"
echo "     byte-identity are hard gates; the artifact is registry-scraped."
echo "     Commit it as SIM_r<round>.json; helper/bench_history.py"
echo "     collates SIM_r*.json and flags p99/capacity regressions.)"
timeout 600 python exp/prod_sim.py /tmp/sim_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/sim_tpu.json')); print(json.dumps({k: {'p99': v['latency_s']['p99'], 'capacity': v['capacity_rows_per_sec_per_replica'], 'ok': v['ok']} for k, v in d['scenarios'].items()}, indent=1))" \
  || echo "   prod sim FAILED on hardware — /tmp/sim_tpu.json + replica logs in the tempdir have the ledger"
echo "=== 11. quality-firewall soak on hardware (ISSUE 12) ==="
echo "    (the three-stage model-quality firewall under data/model faults:"
echo "     poison_rows -> ingest quarantine, label_flip -> pre-publish eval"
echo "     gate, regress_model -> serving canary + automatic rollback."
echo "     On hardware the canary's latency signal judges real device"
echo "     batches, so a generation that only regresses in DEVICE latency"
echo "     (e.g. a shape-bucket blowup) is caught here first.  Hard gates:"
echo "     zero poisoned generations published, zero regressed responses"
echo "     outside the canary fraction, every rollback byte-verified."
echo "     Commit it as CHAOS_QUALITY_r<round>.json; helper/bench_history.py"
echo "     schema-gates it and flags canary-detection-window regressions.)"
timeout 600 python exp/chaos_quality.py /tmp/chaos_quality_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/chaos_quality_tpu.json')); p1=d['phases']['ingest_gate']; p2=d['phases'].get('canary',{}); print(json.dumps({'ok': d['ok'], 'quarantined': p1['quarantined_total'], 'gate_rejections': p1['gate_rejections'], 'rollbacks': p2.get('rollback_count'), 'rollback_byte_verified': p2.get('rollback_byte_verified')}, indent=1))" \
  || echo "   quality soak FAILED — /tmp/chaos_quality_tpu.json.invalid + trainer/replica logs in the tempdir have the ledger"
echo "=== 12. fused boosting window A/B on hardware (ISSUE 13) ==="
echo "    (boost_window=J runs J boosting iterations per device dispatch;"
echo "     on the tunneled chip each saved dispatch is a ~90 ms round trip"
echo "     (BENCH_r05), so this is the lever the CPU A/B could only count,"
echo "     not weigh.  The bench 'window' key reports sec/iter +"
echo "     dispatches/iter + fetches/iter for both arms on the SAME"
echo "     booster.  Flip criterion (docs/PERFORMANCE.md expiry row):"
echo "     sec_per_iter no worse AND dispatches_per_iter <= (1/J)*baseline"
echo "     -> flip the config default boost_window=4; else keep 1 and"
echo "     record why.  Commit the run as BENCH_WINDOW_r<round>.json.)"
BENCH_WINDOW=4 BENCH_PREDICT=0 BENCH_SERVE=0 BENCH_ONLINE=0 BENCH_INGEST=0 \
  BENCH_TELEMETRY=0 BENCH_ITERS=12 timeout 1800 python bench.py \
  > /tmp/bench_window_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/bench_window_tpu.json')); print(json.dumps({'window': d.get('window'), 'dispatches_per_iter': d.get('attrib',{}).get('per_iter',{}).get('dispatches_per_iter')}, indent=1))" \
  || echo "   window A/B FAILED on hardware — /tmp/bench_window_tpu.json + stderr have the ledger"
echo "=== 13. end-to-end trace capture on hardware (ISSUE 14) ==="
echo "    (the causal counterpart of step 9's BENCH_ATTRIB averages: a"
echo "     merged Perfetto timeline of one 2-replica prod-sim fleet on"
echo "     the real chip — loadgen -> serving -> DEVICE batch -> drain"
echo "     chains plus the trainer cycle -> publish -> subscriber links,"
echo "     with every sampled request's stage sum gated against its"
echo "     client-observed latency at one bucket width.  On hardware the"
echo "     device_s stage is real accelerator time, so THIS is where the"
echo "     ~90 ms/tree round trip and any p99 spike become attributable"
echo "     per-request instead of on average.  COMMIT the artifact as"
echo "     TRACE_r<round>.json alongside BENCH_ATTRIB; load the 'trace'"
echo "     member in https://ui.perfetto.dev to read it.)"
PROD_SIM_TRACE_OUT=/tmp/trace_tpu.json timeout 600 \
  python exp/prod_sim.py /tmp/sim_trace_tpu.json --quick \
  && python -c "import json; d=json.load(open('/tmp/trace_tpu.json')); print(json.dumps({'ok': d['ok'], 'gates': d['gates'], 'stage_sum': d['stage_sum']}, indent=1))" \
  || echo "   trace capture FAILED — /tmp/trace_tpu.json + replica logs have the ledger"
echo "    (ad-hoc capture on any task: LGBM_TPU_TRACE_DIR=/tmp/traces"
echo "     python -m lightgbm_tpu task=... ; then"
echo "     python -m lightgbm_tpu.runtime.tracing merge out.json /tmp/traces/trace_*.json)"
echo "=== 14. warm-start bench on hardware (ISSUE 15) ==="
echo "    (the whole window above ran under \$LGBM_TPU_COMPILE_CACHE, so"
echo "     steps 2+ already reused step 1's programs — doctor bundles"
echo "     carry warmup_status.json with the hit/miss ledger.  This step"
echo "     books the ON-HARDWARE cold-start numbers: serving time-to-"
echo "     ready / time-to-first-verified-response for cold vs cache vs"
echo "     manifest-prewarm starts (on a tunneled TPU every compile is a"
echo "     multi-second round trip, so the serving ratios — trend-only"
echo "     on CPU — are real here), the trainer's fused-step startup"
echo "     overhead cold vs warm, and the replica-join-mid-run timing"
echo "     the autoscaler needs.  Byte-identity + zero-retrace are hard"
echo "     gates.  COMMIT the artifact as BENCH_COLD_r<round>.json;"
echo "     helper/bench_history.py schema-gates it and flags >10%"
echo "     startup regressions.)"
BENCH_COLDSTART_PLATFORM=tpu timeout 900 \
  python exp/bench_coldstart.py --artifact /tmp/bench_cold_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/bench_cold_tpu.json')); print(json.dumps({'ok': d['ok'], 'speedup': d['speedup'], 'join_s': d['replica_join']['join_to_first_response_s']}, indent=1))" \
  || echo "   coldstart bench FAILED on hardware — /tmp/bench_cold_tpu.json + child logs in the tempdir have the ledger"
echo "=== 15. wire-speed data plane on hardware (ISSUE 16) ==="
echo "    (the CPU-committed BENCH_WIRE_r16.json proved the binary"
echo "     plane >=5x the JSON plane and >=10k offered req/s with a"
echo "     compiled-C client byte-verifying every response — but on"
echo "     CPU the device_s stage competes with the handlers for the"
echo "     same core.  On hardware the predict dispatch leaves the"
echo "     host, so the closed-loop rates here are the real serving"
echo "     envelope: raise BENCH_WIRE_TREES/LEAVES to production shape"
echo "     (predict no longer drowns the plane) and expect the binary"
echo "     paths to pull further ahead.  COMMIT the artifact as"
echo "     BENCH_WIRE_r<round>.json; helper/bench_history.py"
echo "     schema-gates it and flags >10% same-shape regressions.)"
timeout 900 \
  python exp/bench_wire.py --out /tmp/bench_wire_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/bench_wire_tpu.json')); print(json.dumps({'ok': d['ok'], 'speedup': d['speedup'], 'offered_per_sec': d['offered']['offered_per_sec'], 'gates': d['gates']}, indent=1))" \
  || echo "   wire bench FAILED on hardware — /tmp/bench_wire_tpu.json + stderr have the ledger"
echo "=== 16. elastic fleet soak on hardware (ISSUE 17) ==="
echo "    (the CPU-committed SIM_r17.json proved the control loop — >=10x"
echo "     the r11 offered load, scale-ups inside 15 s, shed only at max"
echo "     replicas, LRU zoo residency, die_at_spawn + SIGKILL churn, all"
echo "     byte-verified — but on ONE core the replicas fight the loadgen"
echo "     for cycles, so spawn_to_ready and the scale-up reaction carry"
echo "     CPU contention.  On hardware predict dispatches leave the host:"
echo "     rerun with more headroom and expect spawn_to_ready_s near the"
echo "     BENCH_COLD join numbers and a lower replica_seconds per million"
echo "     verified.  Watch fleet.scale_up_reaction_s_max and"
echo "     fleet.residency (page_in/evict/defer) — on-device page-in cost"
echo "     is the number the CPU run could only approximate.  COMMIT the"
echo "     artifact as SIM_r<round>.json; helper/bench_history.py collates"
echo "     the fleet series and rejects unverified completions.)"
PROD_SIM_DURATION=60 timeout 900 \
  python exp/prod_sim.py /tmp/sim_fleet_tpu.json --fleet \
  && python -c "import json; d=json.load(open('/tmp/sim_fleet_tpu.json')); print(json.dumps({k: {'ok': v['ok'], 'ups': v['fleet']['scale_ups'], 'downs': v['fleet']['scale_downs'], 'relaunches': v['fleet']['relaunches'], 'reaction_s': v['fleet']['scale_up_reaction_s_max'], 'rs_per_1M': v['fleet']['replica_seconds_per_million_verified'], 'x_r11': v['fleet']['offered_x_r11']} for k, v in d['scenarios'].items()}, indent=1))" \
  || echo "   fleet soak FAILED on hardware — /tmp/sim_fleet_tpu.json + replica logs in the tempdir have the ledger"
echo "=== 17. shared-memory ring plane on hardware (ISSUE 20) ==="
echo "    (the CPU-committed BENCH_WIRE_r20.json proved the ring plane"
echo "     >=2x the single-connection binary-UDS req/s with ZERO"
echo "     steady-state syscalls and ZERO per-request allocations in"
echo "     either ring direction, every response byte-verified — but on"
echo "     ONE core the spinning consumer and the predict loop fight for"
echo "     the same cycles, so the pipelined latency there is queueing,"
echo "     not transport.  On hardware the predict dispatch leaves the"
echo "     host and the doorbell spin gets its own core: expect the"
echo "     sub-millisecond p50 the title promises and a wider shm-vs-uds"
echo "     gap.  Raise LGBM_TPU_SHM_SPIN_S only for the measurement"
echo "     window (an idle client must cost nothing).  The shm_plane"
echo "     section of the same BENCH_WIRE artifact carries it; COMMIT as"
echo "     BENCH_WIRE_r<round>.json — helper/bench_history.py gates the"
echo "     shm series and requires the zero-mismatch + byte-verified"
echo "     flags.)"
timeout 900 \
  python exp/bench_wire.py --out /tmp/bench_wire_shm_tpu.json \
  && python -c "import json; d=json.load(open('/tmp/bench_wire_shm_tpu.json')); p=d['shm_plane']; print(json.dumps({'ok': d['ok'], 'speedup_shm_over_uds': p['speedup_shm_over_uds'], 'win_syscalls': p['win_syscalls'], 'ring_stats_delta': p['ring_stats_delta'], 'gates': d['gates']}, indent=1))" \
  || echo "   shm ring bench FAILED on hardware — /tmp/bench_wire_shm_tpu.json + stderr have the ledger"
