#!/usr/bin/env python
"""Chaos soak for the serving runtime (ISSUE 7 acceptance).

Hammers a live `ServingRuntime` with concurrent clients while THREE
fault families churn underneath it:

* **device kill/stall** — `LGBM_TPU_FAULT=die_at_predict:1` (every
  device batch raises) and `slow_predict:S` (every device batch stalls
  past the predict deadline) are armed and cleared in randomized
  windows: the server must degrade to the host predictor, keep
  answering, and recover to the device path when the window closes;
* **publish churn** — every generation is published by a SUBPROCESS
  publisher that may die torn (`torn_write:1`) or die between the
  generation rename and the manifest write (`die_at_publish:1`); the
  relaunch republishes, and the serving poller must never swap in a
  torn model;
* **overload** — the bounded queue sheds under the stall windows; every
  shed request must carry an explicit machine-readable RETRYABLE
  rejection.

The pins, asserted here and (tier-1-sized) in tests/test_serving.py:

* **zero torn or wrong-generation responses** — every completed
  response names a generation that was actually published, and its
  values are byte-identical to offline `Booster.predict` for that
  generation (host-served responses against the exact f64 host path,
  device-served against the device path — per-row device outputs are
  batch-composition invariant, pinned in tests/test_serving.py);
* **zero drops** — every admitted request completes or is explicitly
  rejected; nothing hangs, nothing vanishes.

Usage:  python exp/chaos_serve.py [generations] [artifact.json]
        (defaults: 16 generations, CHAOS_SERVE_r07.json at the repo root)
        python exp/chaos_serve.py --publish <pub_dir> <gen> <text_file>
        (internal: one subprocess publish, faults via LGBM_TPU_FAULT)
Env:    CHAOS_SERVE_SEED, CHAOS_SERVE_CLIENTS
"""
from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.runtime import publish, resilience  # noqa: E402

#: serving fault windows one churn step draws from (None = quiet step).
#: die_at_predict kills every device batch while armed; slow_predict
#: stalls every device batch past the runtime's predict deadline.
SERVE_FAULT_POOL = [None, "die_at_predict:1", "slow_predict:0.6"]

#: publisher-side faults (the subprocess publisher dies mid-publish and
#: the parent relaunches it — PR 6's churn, now observed from the
#: consuming side).
PUBLISH_FAULT_POOL = [None, None, "torn_write:1", "die_at_publish:1"]


def _train_generations(n_gens: int, rounds: int, seed: int = 7):
    """One continued-training lineage: generation g = g*rounds
    iterations.  Returns (texts, probe, ref_host, ref_dev) — the model
    text per generation plus offline Booster.predict references for the
    probe rows through BOTH serving paths (computed before any fault is
    armed)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster

    rng = np.random.default_rng(seed)
    X = rng.standard_normal((500, 6)).astype(np.float32)
    y = (X[:, 0] + 0.4 * X[:, 1]
         + 0.3 * rng.standard_normal(500) > 0).astype(np.float64)
    bst = lgb.Booster({"objective": "binary", "num_leaves": 15,
                       "verbose": -1, "seed": 7},
                      lgb.Dataset(X, label=y))
    texts: Dict[int, str] = {}
    for g in range(1, n_gens + 1):
        for _ in range(rounds):
            bst.update()
        texts[g] = bst.model_to_string()
    probe = rng.standard_normal((64, 6))
    ref_host, ref_dev = {}, {}
    for g, text in texts.items():
        b = Booster(model_str=text)
        ref_host[g] = b.predict(probe)
        ref_dev[g] = b.predict(probe, device=True)
    return texts, probe, ref_host, ref_dev


def _publish_subprocess(pub_dir: str, gen: int, text_path: str,
                        fault: Optional[str], timeout: float = 60.0
                        ) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("LGBM_TPU_FAULT", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if fault:
        env["LGBM_TPU_FAULT"] = fault
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--publish",
         pub_dir, str(gen), text_path],
        env=env, timeout=timeout, capture_output=True, text=True)


class _Client(threading.Thread):
    """One concurrent client: random probe subsets, bounded retry on
    retryable rejections, bitwise verification of every response against
    the offline reference for the generation it reports."""

    def __init__(self, idx: int, rt, probe, ref_host, ref_dev,
                 published: set, stop_evt: threading.Event):
        super().__init__(name="chaos-client-%d" % idx, daemon=True)
        self.rng = np.random.default_rng(1000 + idx)
        self.rt = rt
        self.probe = probe
        self.ref_host = ref_host
        self.ref_dev = ref_dev
        self.published = published
        self.stop_evt = stop_evt
        self.completed = 0
        self.shed = 0
        self.rejection_reasons: Dict[str, int] = {}
        self.bad_rejections = 0          # non-machine-readable sheds
        self.wrong_generation: List[int] = []
        self.mismatched: List[str] = []
        self.hard_errors: List[str] = []
        self.served_by = {"device": 0, "host": 0}
        self.latencies: List[float] = []

    def _record_rejection(self, e) -> None:
        self.shed += 1
        self.rejection_reasons[e.reason] = \
            self.rejection_reasons.get(e.reason, 0) + 1
        d = e.to_dict()
        if not (e.retryable is True and d.get("retryable") is True
                and d.get("error") == "rejected" and d.get("reason")):
            self.bad_rejections += 1

    def _verify(self, rec, idx) -> None:
        self.completed += 1
        self.served_by[rec.served_by] = \
            self.served_by.get(rec.served_by, 0) + 1
        if rec.generation not in self.published:
            self.wrong_generation.append(rec.generation)
            return
        ref = (self.ref_dev if rec.served_by == "device"
               else self.ref_host)[rec.generation]
        if not np.array_equal(np.asarray(rec.values), ref[idx]):
            self.mismatched.append(
                "gen %d via %s" % (rec.generation, rec.served_by))

    def run(self) -> None:
        from lightgbm_tpu.runtime.serving import ServeRejected
        while not self.stop_evt.is_set():
            burst = self.rng.random() < 0.12
            if burst:
                # load spike: a volley of raw submits with no retry —
                # exactly what the bounded queue must shed explicitly
                pending = []
                for _ in range(12):
                    idx = self.rng.integers(0, len(self.probe), size=4)
                    try:
                        pending.append(
                            (idx, self.rt.submit(self.probe[idx],
                                                 deadline_s=5.0)))
                    except ServeRejected as e:
                        self._record_rejection(e)
                for idx, req in pending:
                    try:
                        self._verify(req.wait(timeout=30), idx)
                    except ServeRejected as e:
                        self._record_rejection(e)
                    except BaseException as e:   # noqa: BLE001 — ledger
                        self.hard_errors.append(
                            "%s: %s" % (type(e).__name__, e))
                continue
            idx = self.rng.integers(0, len(self.probe),
                                    size=int(self.rng.integers(1, 9)))
            t0 = time.perf_counter()
            try:
                rec = self.rt.predict(self.probe[idx], deadline_s=5.0,
                                      attempts=2, seed=self.completed)
            except ServeRejected as e:
                self._record_rejection(e)
                continue
            except BaseException as e:       # noqa: BLE001 — ledger
                self.hard_errors.append("%s: %s" % (type(e).__name__, e))
                continue
            self.latencies.append(time.perf_counter() - t0)
            self._verify(rec, idx)


def run_soak(workdir: str, generations: int = 16, rounds: int = 2,
             clients: int = 6, seed: int = 11,
             serve_fault_pool: Optional[List[Optional[str]]] = None,
             publish_fault_pool: Optional[List[Optional[str]]] = None,
             step_s: float = 0.5) -> Dict:
    """One full soak; returns the machine-readable record (also the
    CHAOS_SERVE_r07.json artifact schema)."""
    from lightgbm_tpu.runtime.serving import ServingRuntime

    t0 = time.monotonic()
    rng = random.Random(seed)
    spool = list(SERVE_FAULT_POOL if serve_fault_pool is None
                 else serve_fault_pool)
    ppool = list(PUBLISH_FAULT_POOL if publish_fault_pool is None
                 else publish_fault_pool)
    pub_dir = os.path.join(workdir, "pub")
    texts, probe, ref_host, ref_dev = _train_generations(generations, rounds)
    text_paths = {}
    for g, text in texts.items():
        text_paths[g] = os.path.join(workdir, "gen_%d_src.txt" % g)
        with open(text_paths[g], "w") as fh:
            fh.write(text)

    published: set = set()
    faults_injected: List[str] = []
    publisher = {"launches": 0, "deaths": 0}
    stop_evt = threading.Event()
    rt = ServingRuntime(publish_dir=pub_dir, params={"verbose": -1},
                        max_queue=16, batch_window_s=0.002,
                        predict_deadline_s=0.25, breaker_cooldown_s=0.2,
                        poll_interval_s=0.03)
    rt.start()
    workers = [_Client(i, rt, probe, ref_host, ref_dev, published,
                       stop_evt) for i in range(clients)]
    try:
        # publish generation 1 cleanly so clients have something to hit
        publisher["launches"] += 1
        r = _publish_subprocess(pub_dir, 1, text_paths[1], None)
        assert r.returncode == 0, r.stderr[-2000:]
        published.add(1)
        for w in workers:
            w.start()

        for gen in range(2, generations + 1):
            serve_fault = rng.choice(spool)
            if serve_fault:
                faults_injected.append(serve_fault)
                os.environ["LGBM_TPU_FAULT"] = serve_fault
            pub_fault = rng.choice(ppool)
            publisher["launches"] += 1
            # the generation is legitimate the instant its file can land
            # (die_at_publish kills the child AFTER the atomic rename, so
            # the poller may swap it in before the subprocess even
            # reports back) — record it before the attempt; the ledger's
            # invariant is that every reported generation's VALUES match
            # that generation's offline reference, torn publishes can
            # never resolve at all
            published.add(gen)
            r = _publish_subprocess(pub_dir, gen, text_paths[gen],
                                    pub_fault)
            if pub_fault:
                faults_injected.append("publish:" + pub_fault)
            if r.returncode != 0:
                # the injected death: a torn/stale publish is on disk;
                # the relaunch republishes the SAME bytes (the trainer's
                # recover-and-republish contract, PR 6)
                publisher["deaths"] += 1
                publisher["launches"] += 1
                r = _publish_subprocess(pub_dir, gen, text_paths[gen],
                                        None)
                assert r.returncode == 0, r.stderr[-2000:]
            # let the poller swap and the clients hammer through the
            # fault window, then clear it and give the breaker a chance
            # to run its recovery probe
            time.sleep(step_s)
            if serve_fault:
                os.environ.pop("LGBM_TPU_FAULT", None)
                time.sleep(step_s / 2)
        # wait for the last swap so post-churn responses prove recovery
        deadline = time.monotonic() + 15
        while (rt.generation() != generations
               and time.monotonic() < deadline):
            time.sleep(0.05)
        time.sleep(step_s)
    finally:
        os.environ.pop("LGBM_TPU_FAULT", None)
        stop_evt.set()
        for w in workers:
            w.join(timeout=30)
        stats = rt.stats()
        rt.stop()

    lat = np.asarray(sum((w.latencies for w in workers), [])) \
        if any(w.latencies for w in workers) else np.asarray([0.0])
    rec = {
        "artifact": "CHAOS_SERVE_r07",
        "t_start": resilience.wallclock(),
        "generations_target": generations,
        "final_generation": rt.generation(),
        "clients": clients,
        "requests_completed": sum(w.completed for w in workers),
        "requests_shed": sum(w.shed for w in workers),
        "rejection_reasons": {
            k: sum(w.rejection_reasons.get(k, 0) for w in workers)
            for w in workers for k in w.rejection_reasons},
        "non_machine_readable_rejections": sum(w.bad_rejections
                                               for w in workers),
        "wrong_generation_responses": sum(len(w.wrong_generation)
                                          for w in workers),
        "mismatched_responses": sum((w.mismatched for w in workers), []),
        "hard_errors": sum((w.hard_errors for w in workers), [])[:10],
        "served_by": {
            "device": sum(w.served_by.get("device", 0) for w in workers),
            "host": sum(w.served_by.get("host", 0) for w in workers)},
        "latency_ms": {
            "p50": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "max": round(float(lat.max()) * 1e3, 3)},
        "faults_injected": faults_injected,
        "publisher": publisher,
        "subscriber_skipped_invalid": sum(
            s.skipped_invalid for s in rt._subs.values()),
        "swaps": stats["swaps"],
        "degradations": stats["degradations"],
        "recoveries": stats["recoveries"],
        "queue_rejections": stats["rejected"],
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    rec["ok"] = bool(
        rec["final_generation"] == generations
        and rec["wrong_generation_responses"] == 0
        and not rec["mismatched_responses"]
        and not rec["hard_errors"]
        and rec["non_machine_readable_rejections"] == 0
        and rec["requests_completed"] > 0
        # churn must actually have exercised both paths when faults ran
        and (not faults_injected or rec["served_by"]["host"] > 0))
    return rec


def main(argv: List[str]) -> int:
    if len(argv) > 1 and argv[1] == "--publish":
        # subprocess mode: ONE publish with whatever LGBM_TPU_FAULT the
        # parent armed (torn_write/die_at_publish make this process die)
        pub_dir, gen, text_path = argv[2], int(argv[3]), argv[4]
        with open(text_path) as fh:
            text = fh.read()
        publish.ModelPublisher(pub_dir, keep_last=0).publish(
            text, meta={"cycle": gen}, generation=gen)
        return 0
    import tempfile
    generations = int(argv[1]) if len(argv) > 1 else 16
    artifact = argv[2] if len(argv) > 2 \
        else os.path.join(REPO, "CHAOS_SERVE_r07.json")
    seed = int(os.environ.get("CHAOS_SERVE_SEED", "11"))
    clients = int(os.environ.get("CHAOS_SERVE_CLIENTS", "6"))
    with tempfile.TemporaryDirectory(prefix="lgbm_chaos_serve_") as wd:
        rec = run_soak(wd, generations=generations, clients=clients,
                       seed=seed)
    resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
    print("chaos serve soak: ok=%s generations=%s/%d completed=%d shed=%d "
          "wrong_gen=%d mismatched=%d degradations=%d recoveries=%d "
          "artifact=%s"
          % (rec["ok"], rec["final_generation"],
             rec["generations_target"], rec["requests_completed"],
             rec["requests_shed"], rec["wrong_generation_responses"],
             len(rec["mismatched_responses"]), rec["degradations"],
             rec["recoveries"], artifact), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
