"""Microbench: per-call + per-row cost of the Pallas segment kernels on TPU.

Timing protocol: every measurement FETCHES a scalar of the result to the
host.  The tunneled axon platform's `block_until_ready` can return before
the remote execution finishes (async-queued identical dispatches once
measured 0.2 ms/call for a kernel whose true cost is ~90 ms), so only
fetch-forced, distinct-input timings are trustworthy here.  Inputs are
perturbed per rep to defeat any dispatch-level caching.
"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops import segment as seg
from lightgbm_tpu.ops import pallas_segment as pseg

print("backend:", jax.default_backend(), flush=True)
rng = np.random.default_rng(0)
N = 1 << 20            # 1M rows
F, B = 28, 256
P = 128
GRAD, HESS, CNT, VAL = F, F + 1, F + 2, F + 3

payload = np.zeros((N + seg.GUARD, P), np.float32)
payload[:N, :F] = rng.integers(0, B, (N, F))
payload[:N, GRAD] = rng.standard_normal(N)
payload[:N, HESS] = rng.random(N) + 0.1
payload[:N, CNT] = 1.0
payload = jnp.asarray(payload)

pred = seg.SplitPredicate(
    col=jnp.int32(2), threshold=jnp.int32(100),
    default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
    missing_type=jnp.int32(0), num_bin=jnp.int32(B),
    default_bin=jnp.int32(0), offset=jnp.int32(0),
    identity=jnp.bool_(True), bitset=jnp.zeros(B, jnp.int32))


def timeit_fetch(fn, reps=7):
    """Median seconds per call; fn(i) must RETURN A HOST SCALAR."""
    fn(0)  # warm (compile)
    ts = []
    for i in range(reps):
        t0 = time.perf_counter()
        fn(i + 1)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def hist_call(count, expand_impl=None):
    def run(i):
        h = pseg.segment_histogram(
            payload, jnp.int32(0), jnp.int32(count - (i % 2)),
            num_features=F, num_bins=B, grad_col=GRAD, hess_col=HESS,
            cnt_col=CNT, **({"expand_impl": expand_impl} if expand_impl
                            else {}))
        return float(np.asarray(h)[0, 0, 2])
    return run


def part_call(kernel, count, **kw):
    def run(i):
        p_ = jnp.asarray(payload)
        a_ = jnp.zeros_like(p_)
        _ = np.asarray(p_)[0, 0]   # ensure uploaded before the clock
        t0 = time.perf_counter()
        out = kernel(p_, a_, jnp.int32(0), jnp.int32(count - (i % 2)), pred,
                     jnp.float32(1.0), jnp.float32(-1.0), VAL, B, **kw)
        nl = int(out[2])
        return time.perf_counter() - t0
    # upload time excluded: run() returns its own measured duration
    run._self_timed = True
    return run


def timeit_self(fn, reps=5):
    fn(0)
    ts = [fn(i + 1) for i in range(reps)]
    return sorted(ts)[len(ts) // 2]


for count in (1 << 15, 1 << 18, 1 << 20):
    t_h = timeit_fetch(hist_call(count))
    t_p = timeit_self(part_call(pseg.partition_segment, count))
    print("count=%8d  hist %8.2f ms (%6.2f ns/row)   part[rmw] %8.2f ms "
          "(%6.2f ns/row)" % (count, t_h * 1e3, t_h / count * 1e9,
                              t_p * 1e3, t_p / count * 1e9), flush=True)

for label, kw in (("acc", dict(roll_place=False)),
                  ("acc+roll", dict(roll_place=True))):
    t_p = timeit_self(part_call(pseg.partition_segment_acc, 1 << 20, **kw))
    print("part[%s] 1M rows: %8.2f ms (%6.2f ns/row)"
          % (label, t_p * 1e3, t_p / (1 << 20) * 1e9), flush=True)

for impl in ("matmul", "repeat"):
    t_h = timeit_fetch(hist_call(1 << 20, expand_impl=impl))
    print("hist[%s] 1M rows: %8.2f ms (%6.2f ns/row)"
          % (impl, t_h * 1e3, t_h / (1 << 20) * 1e9), flush=True)

# dispatch floor: tiny count isolates the fixed per-dispatch cost
t0 = timeit_fetch(hist_call(8))
print("hist count=8 floor: %.2f ms" % (t0 * 1e3), flush=True)
