"""Microbench: per-call + per-row cost of the Pallas segment kernels on TPU."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops import segment as seg
from lightgbm_tpu.ops import pallas_segment as pseg

print("backend:", jax.default_backend(), flush=True)
rng = np.random.default_rng(0)
N = 1 << 20            # 1M rows
F, B = 28, 256
P = 128
GRAD, HESS, CNT, VAL = F, F + 1, F + 2, F + 3

payload = np.zeros((N + seg.GUARD, P), np.float32)
payload[:N, :F] = rng.integers(0, B - 1, (N, F))
payload[:N, GRAD] = rng.standard_normal(N)
payload[:N, HESS] = rng.random(N) + 0.1
payload[:N, CNT] = 1.0
payload = jnp.asarray(payload)
aux = jnp.zeros_like(payload)

pred = seg.SplitPredicate(
    col=jnp.int32(2), threshold=jnp.int32(100),
    default_left=jnp.bool_(True), is_cat=jnp.bool_(False),
    missing_type=jnp.int32(0), num_bin=jnp.int32(B),
    default_bin=jnp.int32(0), offset=jnp.int32(0),
    identity=jnp.bool_(True), bitset=jnp.zeros(B, jnp.int32))


def timeit(fn, reps=20):
    fn()  # warm
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


for count in (1 << 12, 1 << 15, 1 << 18, 1 << 20):
    c = jnp.int32(count)
    t_h = timeit(lambda: pseg.segment_histogram(
        payload, jnp.int32(0), c, num_features=F, num_bins=B,
        grad_col=GRAD, hess_col=HESS, cnt_col=CNT))
    t_p = timeit(lambda: pseg.partition_segment(
        payload, aux, jnp.int32(0), c, pred, jnp.float32(1.0),
        jnp.float32(-1.0), VAL, B)[2])
    print("count=%8d  hist %7.3f ms (%5.2f ns/row)   part %7.3f ms (%5.2f ns/row)"
          % (count, t_h * 1e3, t_h / count * 1e9, t_p * 1e3, t_p / count * 1e9),
          flush=True)

# dispatch floor: count=0
t0 = timeit(lambda: pseg.segment_histogram(
    payload, jnp.int32(0), jnp.int32(0), num_features=F, num_bins=B,
    grad_col=GRAD, hess_col=HESS, cnt_col=CNT))
print("hist count=0 floor: %.3f ms" % (t0 * 1e3), flush=True)
