#!/usr/bin/env python
"""BENCH_COLDSTART: warm-start measurement harness (ISSUE 15).

Startup used to be an unmeasured quantity: PR 11's autoscale policy can
shed but cannot ADD a replica because nobody knew what a replica join
costs.  This harness measures exactly that, in subprocesses (a cold
start only exists in a fresh process — in-process jit caches would lie):

* **cold** — no persistent compile cache, no manifest: today's
  pre-ISSUE-15 start (smallest-bucket prewarm compiles fresh).
* **cache** — ``$LGBM_TPU_COMPILE_CACHE`` armed over a warm
  fingerprinted cache dir, no manifest: the first compile of each
  program becomes a disk load.
* **manifest** — warm cache AND the publish dir's ``warmup.json``
  present: the runtime precompiles every manifest bucket BEFORE
  ``/healthz`` opens, so the first real request pays nothing.

Per mode the child reports **time-to-ready** (ServingRuntime construct →
admission open with a generation loaded) and **time-to-first-verified-
response** (→ first response byte-verified against the offline
predictor for its reported generation + path), plus the steady-state
zero-retrace pin (xla_obs) over follow-up batches and a sha256 of the
response bytes — the parent gates that every mode produced IDENTICAL
predictions.

The **train** section measures the start the fleet actually pays most
for: the fused-step family a `train_online` relaunch recompiles.  A
fresh process builds a booster and times its FIRST iteration (trace +
compile + run) and a steady iteration; ``startup_overhead_s`` =
first − steady isolates the cold-start cost from the fixed work.  The
acceptance gate (``ready_bar``) rides this number: warm
(persistent-cache) startup overhead must be ≥ 2× smaller than cold on
the CPU fallback — the serving-side predictor programs compile in
sub-seconds on XLA:CPU (their per-mode timings are still recorded and
trend-tracked; on a tunneled TPU, where each compile costs seconds,
the serving section is the one to read), and the trained model text is
pinned BYTE-IDENTICAL cold vs warm (a persistent cache can never
change bits).

The **replica_join** section is the prod-sim scenario the autoscaler
needs: while a publisher keeps publishing fresh generations (the live
fleet), a brand-new replica process joins against the SAME publish dir
with cache+manifest armed — ``join_to_first_response_s`` is wall clock
from process spawn (interpreter + jax import included) to its first
byte-verified response.

Usage:
    python exp/bench_coldstart.py [--quick] [--out OUT.json]
    python exp/bench_coldstart.py --artifact BENCH_COLD_r15.json
    python exp/bench_coldstart.py --child cfg.json out.json   (internal)

The artifact is schema-validated (`helper.bench_history.
validate_coldstart_artifact`) before it is written — a malformed run
fails loudly instead of committing zeros; `helper/bench_history.py`
collates BENCH_COLD_r*.json with the same >10% same-shape regression
flags as the bench/sim trajectories (lower is better).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA_VERSION = 1

#: child must come up, serve, verify and pin steady state within this
CHILD_TIMEOUT_S = 300

#: the acceptance bar (ISSUE 15): warm-start (persistent-cache) startup
#: overhead must be at least this many times smaller than the cold
#: start's on the CPU fallback (measured on the trainer's fused-step
#: family, where XLA:CPU compile time actually lives)
READY_SPEEDUP_BAR = 2.0


# ---------------------------------------------------------------------------
# child: one measured start in a fresh process
# ---------------------------------------------------------------------------

def child_main(cfg_path: str, out_path: str) -> int:
    t_entry = time.monotonic()
    with open(cfg_path) as fh:
        cfg = json.load(fh)
    import jax
    jax.config.update("jax_platforms", cfg.get("platform", "cpu"))
    if cfg.get("role") == "train":
        return _train_child(cfg, out_path, t_entry)
    import numpy as np

    from lightgbm_tpu.basic import Booster
    from lightgbm_tpu.runtime import publish as pubmod
    from lightgbm_tpu.runtime import resilience, xla_obs
    from lightgbm_tpu.runtime.serving import ServingRuntime
    import_s = time.monotonic() - t_entry

    t0 = time.monotonic()
    rt = ServingRuntime(publish_dir=cfg["pub_dir"],
                        params={"verbose": -1},
                        poll_interval_s=0.05, batch_window_s=0.001,
                        export_manifest=bool(cfg.get("export_manifest")))
    rt.start()
    deadline = time.monotonic() + 60
    while rt.generation() is None and time.monotonic() < deadline:
        time.sleep(0.02)
    time_to_ready = time.monotonic() - t0

    rng = np.random.default_rng(int(cfg.get("probe_seed", 5)))
    probe = rng.standard_normal((int(cfg["probe_rows"]),
                                 int(cfg["n_features"])))
    rec = rt.predict(probe)
    time_to_first = time.monotonic() - t0
    first_response_unix = time.time()

    # byte-verify against the offline predictor for the reported
    # generation + path (the chaos-soak / loadgen bar)
    gen_path = os.path.join(cfg["pub_dir"],
                            pubmod._gen_name(rec.generation))  # noqa: SLF001
    with open(gen_path, "rb") as fh:
        raw = fh.read().decode("utf-8", "replace")
    split = pubmod._split_validate(raw)                        # noqa: SLF001
    verified = False
    if split is not None:
        ref = Booster(params={"verbose": -1}, model_str=split[0]).predict(
            probe, device=(rec.served_by == "device"))
        verified = bool(np.array_equal(np.asarray(rec.values).reshape(-1),
                                       np.asarray(ref).reshape(-1)))

    # steady-state zero-retrace pin: further same-shape batches compile
    # NOTHING, whichever start mode this was
    xla_obs.mark_steady(True)
    try:
        for _ in range(3):
            rt.predict(probe)
    finally:
        xla_obs.mark_steady(False)
    retraces = list(xla_obs.LEDGER.retraces)

    from lightgbm_tpu.runtime import warmup
    out = {
        "mode": cfg.get("mode"),
        "platform": jax.default_backend(),
        "import_s": round(import_s, 4),
        "time_to_ready_s": round(time_to_ready, 4),
        "time_to_first_response_s": round(time_to_first, 4),
        "first_response_unix": round(first_response_unix, 4),
        "generation": rec.generation,
        "served_by": rec.served_by,
        "verified": verified,
        "pred_sha256": hashlib.sha256(
            np.ascontiguousarray(np.asarray(rec.values)).tobytes()
        ).hexdigest(),
        "steady_retraces": len(retraces),
        "retrace_sites": [r["site"] for r in retraces][:8],
        "compiles": xla_obs.total_compiles(),
        "prewarm_events": rt.prewarm_events,
        "cache": warmup.cache_status(),
    }
    rt.stop()
    resilience.atomic_write(out_path, json.dumps(out, indent=1) + "\n")
    return 0


def _train_child(cfg: Dict[str, Any], out_path: str,
                 t_entry: float) -> int:
    """One trainer start in a fresh process: first iteration (trace +
    compile + run) vs a steady iteration on the same booster — the
    difference IS the cold-start overhead a `train_online` relaunch
    pays before its first cycle can train."""
    import numpy as np

    import bench
    import lightgbm_tpu as lgb
    from lightgbm_tpu.runtime import resilience, warmup
    import_s = time.monotonic() - t_entry
    warmup.maybe_enable_from_env()

    X, y = bench.synth_higgs(int(cfg["rows"]))
    params = {"objective": "binary", "num_leaves": int(cfg["num_leaves"]),
              "max_bin": 255, "learning_rate": 0.1, "verbose": -1,
              "seed": 7}
    t0 = time.monotonic()
    bst = lgb.Booster(dict(params), lgb.Dataset(X, label=y))
    build_s = time.monotonic() - t0
    t0 = time.monotonic()
    bst.update()
    bst._engine.flush()
    first_iter_s = time.monotonic() - t0
    t0 = time.monotonic()
    bst.update()
    bst._engine.flush()
    steady_iter_s = time.monotonic() - t0
    bst._drain()
    model_sha = hashlib.sha256(
        bst._model.save_model_to_string().encode()).hexdigest()
    out = {
        "mode": cfg.get("mode"),
        "import_s": round(import_s, 4),
        "build_s": round(build_s, 4),
        "first_iter_s": round(first_iter_s, 4),
        "steady_iter_s": round(steady_iter_s, 4),
        "startup_overhead_s": round(max(first_iter_s - steady_iter_s,
                                        0.0), 4),
        "model_sha256": model_sha,
        "cache": warmup.cache_status(),
    }
    resilience.atomic_write(out_path, json.dumps(out, indent=1) + "\n")
    return 0


# ---------------------------------------------------------------------------
# parent: orchestrate the modes + the replica join
# ---------------------------------------------------------------------------

def _spawn_child(workdir: str, tag: str, cfg: Dict[str, Any],
                 env: Dict[str, str]) -> Dict[str, Any]:
    cfg_path = os.path.join(workdir, "child_%s.json" % tag)
    out_path = os.path.join(workdir, "child_%s.out.json" % tag)
    with open(cfg_path, "w") as fh:
        json.dump(cfg, fh)
    log_path = os.path.join(workdir, "child_%s.log" % tag)
    t_spawn = time.time()
    with open(log_path, "w") as log:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             cfg_path, out_path],
            cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
            timeout=CHILD_TIMEOUT_S)
    if r.returncode != 0 or not os.path.exists(out_path):
        with open(log_path) as fh:
            raise RuntimeError("coldstart child %s failed (rc=%d): %s"
                               % (tag, r.returncode, fh.read()[-2000:]))
    with open(out_path) as fh:
        rec = json.load(fh)
    rec["spawn_unix"] = round(t_spawn, 4)
    if "first_response_unix" in rec:
        rec["spawn_to_first_response_s"] = round(
            rec["first_response_unix"] - t_spawn, 4)
    return rec


class _Publisher(threading.Thread):
    """The live fleet's trainer stand-in for the replica-join scenario:
    keeps publishing fresh generations while the joining replica comes
    up (so the join races real publish/prune churn)."""

    def __init__(self, pub, make_text, interval_s: float):
        super().__init__(name="coldstart-publisher", daemon=True)
        self.pub = pub
        self.make_text = make_text
        self.interval_s = interval_s
        self.published = 0
        self._halt = threading.Event()

    def run(self) -> None:
        gen = 1
        while not self._halt.wait(self.interval_s):
            gen += 1
            self.pub.publish(self.make_text(gen), meta={"cycle": gen})
            self.published += 1

    def stop(self) -> None:
        self._halt.set()


def run_coldstart(workdir: str, quick: bool = True,
                  platform: Optional[str] = None,
                  log=print) -> Dict[str, Any]:
    import bench
    from lightgbm_tpu.runtime import publish as pubmod

    platform = platform or os.environ.get("BENCH_COLDSTART_PLATFORM") \
        or os.environ.get("LGBTPU_TEST_PLATFORM") or "cpu"
    n_trees, num_leaves, n_feat = (40, 31, 8) if quick else (100, 63, 28)
    probe_rows = int(os.environ.get("BENCH_COLDSTART_PROBE_ROWS", 200))

    pub_dir = os.path.join(workdir, "pub")
    cache_base = os.path.join(workdir, "compile_cache")
    manifest_keep = os.path.join(workdir, "warmup.json.keep")
    manifest_path = os.path.join(pub_dir, "warmup.json")

    def make_text(seed: int) -> str:
        return bench.synth_serving_model(
            n_trees, num_leaves, n_feat, seed=seed).save_model_to_string()

    pub = pubmod.ModelPublisher(pub_dir, keep_last=4, grace_s=600)
    pub.publish(make_text(1), meta={"cycle": 1})

    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = REPO + os.pathsep + base_env.get("PYTHONPATH",
                                                             "")
    base_env.pop("LGBM_TPU_FAULT", None)
    base_env.pop("LGBM_TPU_COMPILE_CACHE", None)
    cache_env = dict(base_env, LGBM_TPU_COMPILE_CACHE=cache_base)

    def cfg(mode: str, export_manifest: bool = False) -> Dict[str, Any]:
        return {"mode": mode, "pub_dir": pub_dir, "platform": platform,
                "n_features": n_feat, "probe_rows": probe_rows,
                "probe_seed": 5, "export_manifest": export_manifest}

    def stash_manifest() -> None:
        if os.path.exists(manifest_path):
            shutil.copyfile(manifest_path, manifest_keep)
            os.unlink(manifest_path)

    modes: Dict[str, Dict[str, Any]] = {}
    # 1. cold: no cache, no manifest; exports the manifest for later
    modes["cold"] = _spawn_child(workdir, "cold", cfg("cold", True),
                                 base_env)
    stash_manifest()
    log("coldstart[cold]: ready %.2fs first_response %.2fs"
        % (modes["cold"]["time_to_ready_s"],
           modes["cold"]["time_to_first_response_s"]))
    # 2. cache seed: populates the persistent cache (diagnostics only —
    #    it runs as cold as mode 1, but with the cache WRITE cost on top)
    modes["cache_seed"] = _spawn_child(workdir, "seed", cfg("cache_seed"),
                                       cache_env)
    stash_manifest()
    # 3. cache: warm persistent cache, no manifest
    modes["cache"] = _spawn_child(workdir, "cache", cfg("cache"),
                                  cache_env)
    stash_manifest()
    log("coldstart[cache]: ready %.2fs first_response %.2fs"
        % (modes["cache"]["time_to_ready_s"],
           modes["cache"]["time_to_first_response_s"]))
    # 4. manifest: warm cache AND the shape manifest back in place
    shutil.copyfile(manifest_keep, manifest_path)
    modes["manifest"] = _spawn_child(workdir, "manifest", cfg("manifest"),
                                     cache_env)
    log("coldstart[manifest]: ready %.2fs first_response %.2fs "
        "(prewarm %s)"
        % (modes["manifest"]["time_to_ready_s"],
           modes["manifest"]["time_to_first_response_s"],
           [e.get("outcome") for e in modes["manifest"]["prewarm_events"]]))

    # 5. the trainer's fused-step family, cold vs warm (the gate): a
    #    fresh trainer process's first-iteration overhead with and
    #    without the persistent cache
    train_rows = int(os.environ.get("BENCH_COLDSTART_TRAIN_ROWS",
                                    8000 if quick else 20000))
    train_leaves = int(os.environ.get("BENCH_COLDSTART_TRAIN_LEAVES", 255))
    train_cache_env = dict(base_env, LGBM_TPU_COMPILE_CACHE=os.path.join(
        workdir, "train_cache"))

    def tcfg(mode: str) -> Dict[str, Any]:
        return {"role": "train", "mode": mode, "platform": platform,
                "rows": train_rows, "num_leaves": train_leaves}

    train = {"rows": train_rows, "num_leaves": train_leaves}
    train["cold"] = _spawn_child(workdir, "train_cold", tcfg("cold"),
                                 base_env)
    train["seed"] = _spawn_child(workdir, "train_seed", tcfg("seed"),
                                 train_cache_env)
    train["warm"] = _spawn_child(workdir, "train_warm", tcfg("warm"),
                                 train_cache_env)
    train["model_identical"] = (train["cold"]["model_sha256"]
                                == train["warm"]["model_sha256"])
    train_speedup = (train["cold"]["startup_overhead_s"]
                     / max(train["warm"]["startup_overhead_s"], 1e-9))
    log("coldstart[train]: startup overhead cold %.2fs vs warm %.2fs "
        "(%.1fx; steady %.2fs/iter; model identical: %s)"
        % (train["cold"]["startup_overhead_s"],
           train["warm"]["startup_overhead_s"], train_speedup,
           train["warm"]["steady_iter_s"], train["model_identical"]))

    # 6. replica join mid-run: live publisher churn + a fresh warm replica
    publisher = _Publisher(pub, make_text, interval_s=1.0)
    publisher.start()
    try:
        join = _spawn_child(workdir, "join", cfg("join"), cache_env)
    finally:
        publisher.stop()
        publisher.join(timeout=10)
    replica_join = {
        "mode": "manifest",
        "join_to_first_response_s": join["spawn_to_first_response_s"],
        "time_to_ready_s": join["time_to_ready_s"],
        "time_to_first_response_s": join["time_to_first_response_s"],
        "import_s": join["import_s"],
        "generation_served": join["generation"],
        "generations_published_during_join": publisher.published,
        "verified": join["verified"],
        "steady_retraces": join["steady_retraces"],
    }
    log("coldstart[join]: spawn->first verified response %.2fs "
        "(%d generations published during the join)"
        % (replica_join["join_to_first_response_s"],
           replica_join["generations_published_during_join"]))

    gate_modes = ("cold", "cache", "manifest")
    hashes = {modes[m]["pred_sha256"] for m in gate_modes}
    ready_speedup = (modes["cold"]["time_to_ready_s"]
                     / max(modes["manifest"]["time_to_ready_s"], 1e-9))
    first_speedup = (modes["cold"]["time_to_first_response_s"]
                     / max(modes["manifest"]["time_to_first_response_s"],
                           1e-9))
    rec = {
        "schema_version": SCHEMA_VERSION,
        "platform": modes["cold"]["platform"],
        "n_trees": n_trees, "num_leaves": num_leaves,
        "n_features": n_feat, "probe_rows": probe_rows,
        "modes": modes,
        "train": train,
        "speedup": {
            # the acceptance gate: warm-start vs cold startup overhead
            # on the trainer's fused-step family (XLA compile lives
            # there on CPU; serving compiles are sub-second disk-cheap)
            "train_startup_overhead_cold_over_warm": round(train_speedup,
                                                           2),
            "ready_bar": READY_SPEEDUP_BAR,
            # trend-tracked serving ratios (compile-light on XLA:CPU;
            # the hardware window is where these move)
            "serve_ready_cold_over_manifest": round(ready_speedup, 2),
            "serve_first_response_cold_over_manifest": round(first_speedup,
                                                             2),
        },
        "predictions_identical": len(hashes) == 1,
        "replica_join": replica_join,
        "note": "cold = no persistent cache/manifest; cache = warm "
                "fingerprinted jax compilation cache; manifest = cache + "
                "warmup.json bucket prewarm before /healthz opens.  "
                "Byte-identity and the zero-retrace pin hold under every "
                "start mode; join runs against live publish churn; the "
                ">=2x gate rides the trainer's startup overhead "
                "(first-iteration minus steady-iteration wall), cold vs "
                "warm persistent cache, with the trained model pinned "
                "byte-identical.",
    }
    rec["ok"] = bool(
        rec["predictions_identical"]
        and all(modes[m]["verified"] for m in gate_modes)
        and all(modes[m]["steady_retraces"] == 0 for m in gate_modes)
        and all(modes[m]["served_by"] == "device" for m in gate_modes)
        and replica_join["verified"]
        and replica_join["steady_retraces"] == 0
        and train["model_identical"]
        and train_speedup >= READY_SPEEDUP_BAR)
    return rec


def main(argv: List[str]) -> int:
    if len(argv) > 1 and argv[1] == "--child":
        return child_main(argv[2], argv[3])
    import tempfile

    from lightgbm_tpu.runtime import resilience
    quick = "--quick" in argv
    out_path = None
    artifact = None
    args = argv[1:]
    for flag, slot in (("--out", "out"), ("--artifact", "artifact")):
        if flag in args:
            i = args.index(flag)
            v = args[i + 1]
            if slot == "out":
                out_path = v
            else:
                artifact = v
    with tempfile.TemporaryDirectory(prefix="lgbm_coldstart_") as wd:
        rec = run_coldstart(wd, quick=quick or artifact is None)
    if artifact:
        name = os.path.splitext(os.path.basename(artifact))[0]
        rec = dict({"artifact": name}, **rec)
        from helper.bench_history import validate_coldstart_artifact
        problems = validate_coldstart_artifact(rec)
        if problems:
            print("bench_coldstart: INVALID artifact: %s"
                  % "; ".join(problems))
            return 2
        resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
        print("bench_coldstart: ok=%s -> %s" % (rec["ok"], artifact))
    elif out_path:
        resilience.atomic_write(out_path, json.dumps(rec) + "\n")
        print("bench_coldstart: ok=%s -> %s" % (rec["ok"], out_path))
    else:
        print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
