#!/usr/bin/env python
"""Chaos soak for the continuous-training service (ISSUE 6 acceptance).

Runs `task=train_online` to a target number of publish cycles while a
relauncher injects a randomized `LGBM_TPU_FAULT` into every launch
(abrupt deaths, preemption signals, torn publishes, mid-publish deaths,
corrupted snapshots, stage stalls) and a high-frequency subscriber
polls the publish directory throughout.  The two pins, asserted here
and in tests/test_continuous.py:

* **zero corrupt observations** — the subscriber never once resolves a
  torn, partial, or checksum-invalid model (torn files on disk are
  fine; RESOLVING one is the failure);
* **byte-identical generations** — every published generation's model
  text equals the same generation from an uninterrupted baseline run
  (deaths rewind to the last cycle boundary and replay
  deterministically; republishes reuse the snapshot's own model text).

Usage:  python exp/chaos.py [cycles] [artifact.json]
        (defaults: 24 cycles, CHAOS_r06.json at the repo root)
Env:    CHAOS_SEED, CHAOS_MAX_FAULTS, CHAOS_LAUNCH_TIMEOUT
"""
from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.runtime import publish, resilience  # noqa: E402

#: service parameters shared by the baseline and every churn launch —
#: byte-identity is only meaningful when the training run is otherwise
#: identical.  bagging + feature_fraction keep the host RNG streams in
#: play (their state crossing kill/resume boundaries is the hard part).
TRAIN_PARAMS = ["objective=binary", "num_leaves=15", "bagging_freq=2",
                "bagging_fraction=0.7", "feature_fraction=0.8", "seed=7",
                "verbose=-1"]

#: the fault pool one churn launch draws from.  `{K}` is replaced with an
#: iteration shortly AHEAD of current progress (a fault behind the clock
#: would either never fire or fire before any work happened — both
#: useless).  The relauncher injects `max_faulted_launches` of these,
#: then lets a clean launch carry the service to its cycle target.
FAULT_POOL = [
    "sigterm_at_iter:{K}",
    "die_at_iter:{K}",
    "torn_write:1",
    "die_at_publish:1",
    "corrupt_snapshot,die_at_iter:{K}",
]


def make_data(path: str, n: int = 400, f: int = 6, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] + 0.4 * X[:, 1]
         + 0.3 * rng.standard_normal(n) > 0).astype(np.float64)
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")


def _service_env(fault: Optional[str]) -> Dict[str, str]:
    env = dict(os.environ)
    env.pop("LGBM_TPU_FAULT", None)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
                "JAX_COMPILATION_CACHE_DIR": "/tmp/lgbtpu_jax_cache",
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1"})
    if fault:
        env["LGBM_TPU_FAULT"] = fault
    return env


def _service_args(workdir: str, cycles: int, rounds: int, interval: float,
                  extra: Optional[List[str]] = None) -> List[str]:
    return (["task=train_online", "data=train.tsv", "output_model=m.txt",
             "online_cycles=%d" % cycles, "online_rounds=%d" % rounds,
             "online_interval=%g" % interval]
            + TRAIN_PARAMS + (extra or []))


def run_service(workdir: str, cycles: int, rounds: int = 2,
                interval: float = 0.0, fault: Optional[str] = None,
                extra: Optional[List[str]] = None,
                timeout: float = 180.0) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu"]
        + _service_args(workdir, cycles, rounds, interval, extra),
        cwd=workdir, env=_service_env(fault), timeout=timeout,
        capture_output=True, text=True)


def _progress_iters(workdir: str) -> int:
    """Current training progress (total iterations) as the relauncher
    sees it: the newest valid snapshot's counter, falling back to 0."""
    _, state = resilience.find_resume_snapshot(
        os.path.join(workdir, "m.txt"), log=_QuietLog())
    return int(state["total_iter"]) if state else 0


class _QuietLog:
    def warning(self, *a):
        pass

    info = warning


class Poller(threading.Thread):
    """High-frequency subscriber: resolves the newest generation over and
    over, deep-validating each NEW (generation, bytes) it sees by parsing
    the model text with the real model loader.  `corrupt_observed` is the
    chaos ledger — it must end at zero."""

    def __init__(self, pub_dir: str, hz: float = 50.0):
        super().__init__(name="chaos-poller", daemon=True)
        self.sub = publish.ModelSubscriber(pub_dir, attempts=1)
        self.period = 1.0 / hz
        self.stop_evt = threading.Event()
        self.polls = 0
        self.corrupt_observed = 0
        self.errors: List[str] = []
        self.seen: Dict[int, str] = {}           # generation -> model text

    def _deep_validate(self, rec) -> None:
        from lightgbm_tpu.models.gbdt_model import GBDTModel
        try:
            model = GBDTModel.load_model_from_string(rec.model_text)
            if model.current_iteration <= 0:
                raise ValueError("empty model")
        except Exception as e:                   # noqa: BLE001 — ledger
            self.corrupt_observed += 1
            self.errors.append("generation %d: %s" % (rec.generation, e))

    def run(self) -> None:
        while not self.stop_evt.is_set():
            self.polls += 1
            rec = self.sub.resolve_once()
            if rec is not None and self.seen.get(rec.generation) \
                    != rec.model_text:
                if rec.generation in self.seen:
                    # a generation's bytes may only ever change from a
                    # torn file to the repaired republish — and a torn
                    # file can never resolve; seeing two DIFFERENT valid
                    # texts for one generation would be a lie to servers
                    self.corrupt_observed += 1
                    self.errors.append(
                        "generation %d changed bytes after publication"
                        % rec.generation)
                else:
                    self._deep_validate(rec)
                    self.seen[rec.generation] = rec.model_text
            self.stop_evt.wait(self.period)

    def stop(self) -> None:
        self.stop_evt.set()
        self.join(timeout=10)


def run_soak(workdir: str, cycles: int = 24, rounds: int = 2,
             interval: float = 0.05, seed: int = 11,
             max_faulted_launches: Optional[int] = None,
             launch_timeout: float = 180.0,
             extra_args: Optional[List[str]] = None,
             fault_pool: Optional[List[Optional[str]]] = None) -> Dict:
    """One full soak: baseline + churn + comparison.  Returns the
    machine-readable record (also the CHAOS_r06.json artifact schema)."""
    t0 = time.monotonic()
    rng = random.Random(seed)
    pool = list(FAULT_POOL if fault_pool is None else fault_pool)
    base_dir = os.path.join(workdir, "baseline")
    churn_dir = os.path.join(workdir, "churn")
    os.makedirs(base_dir)
    os.makedirs(churn_dir)
    make_data(os.path.join(base_dir, "train.tsv"))
    make_data(os.path.join(churn_dir, "train.tsv"))

    # -- baseline: one uninterrupted run, every generation retained ----------
    r = run_service(base_dir, cycles, rounds, interval,
                    extra=["publish_retention=0"] + (extra_args or []),
                    timeout=launch_timeout * 2)
    if r.returncode != 0:
        raise RuntimeError("baseline service failed rc=%d\n%s"
                           % (r.returncode, (r.stderr or "")[-2000:]))
    baseline: Dict[int, str] = {}
    for gen, path in publish.generation_paths(
            os.path.join(base_dir, "m.txt.pub")):
        ok_gen, reason = publish.validate_generation(path)
        assert ok_gen, (path, reason)
        with open(path) as fh:
            baseline[gen] = publish._split_validate(fh.read())[0]

    # -- churn: relaunch under randomized faults while a subscriber polls ----
    poller = Poller(os.path.join(churn_dir, "m.txt.pub"))
    poller.start()
    launches: List[Dict] = []
    max_faults = max_faulted_launches if max_faulted_launches is not None \
        else int(os.environ.get("CHAOS_MAX_FAULTS", "10"))
    ok = False
    try:
        for _attempt in range(cycles + 12):
            faulted = sum(1 for lnch in launches if lnch["fault"])
            fault = rng.choice(pool) if faulted < max_faults else None
            if fault and "{K}" in fault:
                fault = fault.replace(
                    "{K}", str(_progress_iters(churn_dir)
                               + rng.randint(1, 2 * rounds)))
            r = run_service(churn_dir, cycles, rounds, interval,
                            fault=fault, extra=extra_args,
                            timeout=launch_timeout)
            launches.append({"fault": fault, "rc": r.returncode})
            # rc 0 = target reached OR clean preemption exit; only the
            # former ends the churn (a preempted launch leaves the latest
            # generation short of the target)
            if r.returncode == 0 and _latest_gen(churn_dir) >= cycles:
                ok = True
                break
    finally:
        time.sleep(0.2)                  # let the poller see the last gen
        poller.stop()

    # -- comparison ----------------------------------------------------------
    churn_final: Dict[int, str] = {}
    for gen, path in publish.generation_paths(
            os.path.join(churn_dir, "m.txt.pub")):
        with open(path) as fh:
            split = publish._split_validate(fh.read())
        if split is not None:
            churn_final[gen] = split[0]
    observed = dict(poller.seen)
    observed.update(churn_final)         # pruned-before-polled gens, if any
    mismatched = [g for g, text in observed.items()
                  if baseline.get(g) is not None and baseline[g] != text]
    checked = [g for g in observed if baseline.get(g) is not None]

    rec = {
        "artifact": "CHAOS_r06",
        "t_start": resilience.wallclock(),
        "cycles_target": cycles,
        "cycles_run": max(observed) if observed else 0,
        "ok": bool(ok and max(observed or [0]) >= cycles),
        "launches": len(launches),
        "faults_injected": [lnch["fault"] for lnch in launches
                            if lnch["fault"]],
        "launch_rcs": [lnch["rc"] for lnch in launches],
        "subscriber": {
            "polls": poller.polls,
            "resolved": poller.sub.resolved_count,
            "skipped_invalid": poller.sub.skipped_invalid,
            "corrupt_observed": poller.corrupt_observed,
            "corruption_errors": poller.errors,
        },
        "byte_identity": {
            "generations_checked": len(checked),
            "mismatched": mismatched,
        },
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    rec["ok"] = bool(rec["ok"] and poller.corrupt_observed == 0
                     and not mismatched
                     and len(checked) >= cycles)
    return rec


def _latest_gen(workdir: str) -> int:
    gens = publish.generation_paths(os.path.join(workdir, "m.txt.pub"))
    return gens[0][0] if gens else 0


def main(argv: List[str]) -> int:
    import tempfile
    cycles = int(argv[1]) if len(argv) > 1 else 24
    artifact = argv[2] if len(argv) > 2 else os.path.join(REPO,
                                                          "CHAOS_r06.json")
    seed = int(os.environ.get("CHAOS_SEED", "11"))
    timeout = float(os.environ.get("CHAOS_LAUNCH_TIMEOUT", "180"))
    with tempfile.TemporaryDirectory(prefix="lgbm_chaos_") as wd:
        rec = run_soak(wd, cycles=cycles, seed=seed,
                       launch_timeout=timeout)
    resilience.atomic_write(artifact, json.dumps(rec, indent=1) + "\n")
    print("chaos soak: ok=%s cycles=%d/%d launches=%d faults=%d "
          "polls=%d corrupt_observed=%d mismatched=%d artifact=%s"
          % (rec["ok"], rec["cycles_run"], rec["cycles_target"],
             rec["launches"], len(rec["faults_injected"]),
             rec["subscriber"]["polls"],
             rec["subscriber"]["corrupt_observed"],
             len(rec["byte_identity"]["mismatched"]), artifact),
          flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
