"""Measure TPU primitive throughput to pick the histogram architecture.

Candidates for the hot path (reference: dense_bin.hpp ConstructHistogram,
ocl/histogram256.cl):
  A. one-hot einsum variants (current approach, f32 vs bf16, layout flips)
  B. Pallas chunked one-hot-in-VMEM kernel
  C. row gather (physical DataPartition) feasibility: jnp.take throughput
  D. scatter-add, sort, cumsum (partition machinery)
"""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

N = 2 ** 21
F = 28
B = 256
CHUNK = 16384

rng = np.random.default_rng(0)
bins_np = rng.integers(0, B, size=(F, N), dtype=np.uint8)
vals_np = rng.standard_normal((N, 3)).astype(np.float32)

bins = jnp.asarray(bins_np)
vals = jnp.asarray(vals_np)


def timeit(name, fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:50s} {dt*1e3:10.2f} ms")
    return dt


# ---- A. einsum one-hot variants ------------------------------------------
@jax.jit
def hist_einsum_f32(bins, vals):
    nchunk = N // CHUNK
    bins_c = bins.reshape(F, nchunk, CHUNK).transpose(1, 0, 2)
    vals_c = vals.reshape(nchunk, CHUNK, 3)

    def body(acc, xs):
        b, v = xs
        iota = lax.broadcasted_iota(jnp.int32, (1, 1, B), 2)
        onehot = (b.astype(jnp.int32)[:, :, None] == iota).astype(jnp.float32)
        return acc + jnp.einsum("fcb,cd->fbd", onehot, v,
                                preferred_element_type=jnp.float32), None

    acc0 = jnp.zeros((F, B, 3), jnp.float32)
    h, _ = lax.scan(body, acc0, (bins_c, vals_c))
    return h


@jax.jit
def hist_einsum_bf16(bins, vals):
    nchunk = N // CHUNK
    bins_c = bins.reshape(F, nchunk, CHUNK).transpose(1, 0, 2)
    vals_c = vals.astype(jnp.bfloat16).reshape(nchunk, CHUNK, 3)

    def body(acc, xs):
        b, v = xs
        iota = lax.broadcasted_iota(jnp.int32, (1, 1, B), 2)
        onehot = (b.astype(jnp.int32)[:, :, None] == iota).astype(jnp.bfloat16)
        return acc + jnp.einsum("fcb,cd->fbd", onehot, v,
                                preferred_element_type=jnp.float32), None

    acc0 = jnp.zeros((F, B, 3), jnp.float32)
    h, _ = lax.scan(body, acc0, (bins_c, vals_c))
    return h


@jax.jit
def hist_einsum_valsT(bins, vals):
    # output [F, 3, B]: per feature [3, C] x [C, B]; output sublane dim = 3
    nchunk = N // CHUNK
    bins_c = bins.reshape(F, nchunk, CHUNK).transpose(1, 0, 2)
    valsT = vals.T.astype(jnp.bfloat16)  # [3, N]
    valsT_c = valsT.reshape(3, nchunk, CHUNK).transpose(1, 0, 2)

    def body(acc, xs):
        b, vT = xs
        iota = lax.broadcasted_iota(jnp.int32, (1, 1, B), 2)
        onehot = (b.astype(jnp.int32)[:, :, None] == iota).astype(jnp.bfloat16)
        return acc + jnp.einsum("dc,fcb->fdb", vT, onehot,
                                preferred_element_type=jnp.float32), None

    acc0 = jnp.zeros((F, 3, B), jnp.float32)
    h, _ = lax.scan(body, acc0, (bins_c, valsT_c))
    return h


# ---- B. Pallas chunked kernel --------------------------------------------
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PCHUNK = 2048

def _hist_kernel(bins_ref, vals_ref, out_ref):
    # bins_ref [F, PCHUNK] int32 block; vals_ref [8, PCHUNK] bf16 (3 used rows)
    # out_ref [F, 8, B] f32 accumulated across grid
    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)
    vT = vals_ref[:]  # [8, PCHUNK] bf16
    iota = lax.broadcasted_iota(jnp.int32, (PCHUNK, B), 1)
    for f in range(F):
        onehot = (bins_ref[f, :][:, None] == iota).astype(jnp.bfloat16)
        out_ref[f] += jnp.dot(vT, onehot, preferred_element_type=jnp.float32)


@jax.jit
def hist_pallas(bins, vals):
    nchunk = N // PCHUNK
    valsT = jnp.zeros((8, N), jnp.bfloat16).at[:3].set(vals.T.astype(jnp.bfloat16))
    grid = (nchunk,)
    out = pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((F, PCHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, PCHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((F, 8, B), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F, 8, B), jnp.float32),
    )(bins.astype(jnp.int32), valsT)
    return out


# ---- C/D. partition machinery --------------------------------------------
idx_np = rng.permutation(N).astype(np.int32)
idx = jnp.asarray(idx_np)
bins_rows_np = np.ascontiguousarray(
    np.pad(bins_np.T, ((0, 0), (0, 4))))  # [N, 32] uint8
bins_rows = jnp.asarray(bins_rows_np)

take_rows = jax.jit(lambda a, i: jnp.take(a, i, axis=0))
take_minor = jax.jit(lambda a, i: jnp.take(a, i, axis=1))
take_1d = jax.jit(lambda a, i: jnp.take(a, i))


@jax.jit
def scatter_add_1d(idx, v):
    return jnp.zeros(N, jnp.float32).at[idx].add(v)


@jax.jit
def sort_pair(keys, payload):
    return lax.sort((keys, payload), num_keys=1)


@jax.jit
def cumsum_n(v):
    return jnp.cumsum(v)


if __name__ == "__main__":
    print("backend:", jax.default_backend())
    timeit("einsum one-hot f32 (current)", hist_einsum_f32, bins, vals)
    timeit("einsum one-hot bf16", hist_einsum_bf16, bins, vals)
    timeit("einsum valsT bf16 [3,C]x[C,B]", hist_einsum_valsT, bins, vals)
    try:
        h = hist_pallas(bins, vals)
        href = hist_einsum_f32(bins, vals)
        err = float(jnp.max(jnp.abs(h[:, :3].transpose(0, 2, 1) - href)))
        print("pallas max err vs f32:", err)
        timeit("pallas chunked bf16 dot", hist_pallas, bins, vals)
    except Exception as e:
        print("pallas failed:", repr(e))
    timeit("take rows [N,32]u8 random", take_rows, bins_rows, idx)
    timeit("take minor [F,N]u8 random", take_minor, bins, idx)
    timeit("take 1d f32 random", take_1d, vals[:, 0], idx)
    timeit("scatter-add 1d f32 random", scatter_add_1d, idx, vals[:, 0])
    timeit("lax.sort (u8 key, i32 payload)", sort_pair,
           bins[0], jnp.arange(N, dtype=jnp.int32))
    timeit("cumsum f32 N", cumsum_n, vals[:, 0])
